"""GC engine registry.

Mirrors the reference's extension factory switch on ``uigc.engine``
(reference: UIGC.scala:12-19).  Engines: "crgc" (alias "tpu-crgc", the
default, TPU-accelerated), "mac" (weighted reference counting + cycle
detection), "manual" (GC off), and "drl" (reference listing; selectable
here, unlike the reference where it is dead code — UIGC.scala:14-18).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from .engine import Engine, TerminationDecision

if TYPE_CHECKING:  # pragma: no cover
    from ..runtime.system import ActorSystem


def create_engine(system: "ActorSystem") -> Engine:
    name = system.config.get_string("uigc.engine")
    if name in ("crgc", "tpu-crgc"):
        from .crgc.engine import CRGC

        return CRGC(system)
    if name == "mac":
        from .mac.engine import MAC

        return MAC(system)
    if name == "manual":
        from .manual import Manual

        return Manual(system)
    if name == "drl":
        from .drl.engine import DRL

        return DRL(system)
    raise ValueError(f"unknown uigc.engine: {name!r}")


__all__ = ["Engine", "TerminationDecision", "create_engine"]
