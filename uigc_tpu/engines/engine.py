"""The pluggable GC-engine SPI.

Mirrors the reference's ``Engine`` trait: 13 hook pairs through which every
GC-relevant action in the user API funnels (reference:
src/main/scala/edu/illinois/osl/uigc/engines/Engine.scala:19-223), plus the
remoting interception hooks (Engine.scala:225-276).  Python's dynamic
typing removes the need for the reference's ``*Impl``/cast bridging, so
each hook appears once.
"""

from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Any, Callable, Iterable, Optional

from ..interfaces import GCMessage, Refob, SpawnInfo, State

if TYPE_CHECKING:  # pragma: no cover
    from ..runtime.cell import ActorCell
    from ..runtime.context import ActorContext
    from ..runtime.signals import Signal
    from ..runtime.system import ActorSystem


class TerminationDecision(enum.Enum):
    """Verdicts returned by on_idle / post_signal
    (reference: Engine.scala:11-16)."""

    SHOULD_STOP = "should_stop"
    SHOULD_CONTINUE = "should_continue"
    UNHANDLED = "unhandled"


class EngineTap:
    """Observation points for correctness tooling (uigc_tpu/analysis).

    An engine with a non-None ``tap`` calls these from its hook
    implementations; all calls are cheap no-ops by default.  The taps
    observe, never mutate: ``on_release`` fires *before* the engine
    deactivates the refob so the tap can see prior state, and
    ``on_send`` fires before delivery so a tap-side send count always
    happens-before the matching receive.  No reference analogue — the
    reference debugs with in-source asserts instead."""

    def on_send(self, target: "ActorCell", remote: bool = False) -> None:
        """An application message is about to be delivered to ``target``."""

    def on_recv(self, cell: "ActorCell", crossed: bool = False) -> None:
        """``cell`` is receiving a (non-external) application message;
        ``crossed`` marks messages that crossed a node boundary."""

    def on_create(self, owner: "ActorCell", target: "ActorCell") -> None:
        """A reference to ``target`` was created for ``owner``."""

    def on_release(self, ref: Any, already_released: bool = False) -> None:
        """``ref`` is about to be released; ``already_released`` means the
        engine had already seen a release for it (a protocol violation)."""

    def on_stop_decision(self, cell: "ActorCell", msg: Any) -> None:
        """The engine decided ``cell`` SHOULD_STOP after processing
        ``msg`` (called by the runtime before the stop is initiated)."""

    def on_migrate_out(self, cell: "ActorCell", key: str) -> None:
        """``cell`` (a sharded entity, uigc_tpu/cluster) captured its
        state for a live migration and is about to stop.  Its remaining
        local send/recv balance moves to another node's books, so local
        balance comparisons for it are meaningless from here on — the
        sanitizer taints it, exactly like a message that crossed a node
        boundary."""

    def on_migrate_in(self, cell: "ActorCell", key: str) -> None:
        """``cell`` was reconstructed from a migrated snapshot.  Its
        history (creates/sends recorded under the old incarnation's uid)
        lives on another node; local ground-truth counters must not be
        compared against it."""


class Engine:
    """A GC engine: a collection of hooks and datatypes used by the
    runtime.  One instance per ActorSystem (reference: Engine.scala:19)."""

    def __init__(self, system: "ActorSystem"):
        self.system = system
        #: optional :class:`EngineTap` installed by the sanitizer.
        self.tap: Optional[EngineTap] = None
        #: optional wake profiler (uigc_tpu/telemetry/profile.py),
        #: installed by Telemetry.attach; engines with a periodic
        #: collector consult it per wake.
        self.wake_profiler: Optional[Any] = None
        #: optional liveness inspector (uigc_tpu/telemetry/inspect.py),
        #: installed by Telemetry.attach; the collector feeds it one
        #: read-only callback per wake (flight recorder, leak watchdog)
        #: and consults ``parent_capture`` to gate why-live provenance.
        self.liveness_inspector: Optional[Any] = None
        #: optional device observatory (uigc_tpu/telemetry/device.py),
        #: installed by Telemetry.attach; the collector feeds it one
        #: read-only ledger sample per wake (same isolation discipline
        #: as the inspector).
        self.device_observatory: Optional[Any] = None

    # -- Root-actor support ------------------------------------------- #

    def root_message(self, payload: Any, refs: Iterable[Refob]) -> GCMessage:
        """Wrap an external message for delivery to a root actor
        (reference: Engine.scala:28-31)."""
        raise NotImplementedError

    def root_spawn_info(self) -> SpawnInfo:
        """SpawnInfo marking an actor as a root (reference: Engine.scala:35-38)."""
        raise NotImplementedError

    def to_root_refob(self, cell: "ActorCell") -> Refob:
        """Produce a refob for a root actor's cell (reference: Engine.scala:41-44)."""
        raise NotImplementedError

    # -- Lifecycle ----------------------------------------------------- #

    def init_state(self, cell: "ActorCell", spawn_info: SpawnInfo) -> State:
        """Compute the initial GC state of a managed actor
        (reference: Engine.scala:48-60)."""
        raise NotImplementedError

    def get_self_ref(self, state: State, cell: "ActorCell") -> Refob:
        """This actor's refob to itself (reference: Engine.scala:64-76)."""
        raise NotImplementedError

    def spawn(
        self,
        factory: Callable[[SpawnInfo], "ActorCell"],
        state: State,
        ctx: "ActorContext",
    ) -> Refob:
        """Spawn a managed actor (reference: Engine.scala:79-94)."""
        raise NotImplementedError

    # -- Message path -------------------------------------------------- #

    def send_message(
        self,
        ref: Refob,
        msg: Any,
        refs: Iterable[Refob],
        state: State,
        ctx: "ActorContext",
    ) -> None:
        """Send an application message through a refob
        (reference: Engine.scala:97-118)."""
        raise NotImplementedError

    def on_message(
        self, msg: GCMessage, state: State, ctx: "ActorContext"
    ) -> Optional[Any]:
        """Intercept a delivered message; return the app payload, or None
        for engine-internal control messages (reference: Engine.scala:120-135)."""
        raise NotImplementedError

    def on_idle(
        self, msg: GCMessage, state: State, ctx: "ActorContext"
    ) -> TerminationDecision:
        """Called after the user handler for each message
        (reference: Engine.scala:137-152)."""
        raise NotImplementedError

    # -- Signals ------------------------------------------------------- #

    def pre_signal(self, signal: "Signal", state: State, ctx: "ActorContext") -> None:
        """(reference: Engine.scala:154-169)"""

    def post_signal(
        self, signal: "Signal", state: State, ctx: "ActorContext"
    ) -> TerminationDecision:
        """(reference: Engine.scala:171-186)"""
        return TerminationDecision.UNHANDLED

    # -- Reference management ------------------------------------------ #

    def create_ref(
        self, target: Refob, owner: Refob, state: State, ctx: "ActorContext"
    ) -> Refob:
        """Create a reference to ``target`` destined for ``owner``
        (reference: Engine.scala:188-206)."""
        raise NotImplementedError

    def release(
        self, releasing: Iterable[Refob], state: State, ctx: "ActorContext"
    ) -> None:
        """Release references (reference: Engine.scala:208-223)."""
        raise NotImplementedError

    # -- Remoting interception ----------------------------------------- #
    # The fabric instantiates these per link.  Default: pass-through, like
    # the reference's default GraphStage logic (Engine.scala:225-276).

    def spawn_egress(self, link: Any) -> Any:
        """Return an egress interceptor for an outbound link, or None for
        pass-through."""
        return None

    def spawn_ingress(self, link: Any) -> Any:
        """Return an ingress interceptor for an inbound link, or None for
        pass-through."""
        return None

    # -- Dead letters -------------------------------------------------- #

    def on_dead_letter(self, cell: "ActorCell", msg: Any) -> None:
        """Called when a message is delivered to a terminated actor.

        ``cell`` is the addressee as the runtime can still name it: a
        terminated-but-reachable ``ActorCell``, or — on a cross-process
        fabric — the tombstone proxy for a uid that no longer resolves
        (runtime/node.py routes post-mortem frames here so the sender's
        already-stamped send still balances).  Implementations must not
        assume a live local cell; only its identity key matters.

        No reference analogue as an SPI hook; engines that track message
        balances (CRGC) use this to account undelivered sends the way the
        reference's ingress stages account admitted messages across node
        boundaries (reference: IngressEntry.java:91-100)."""

    # -- Shutdown ------------------------------------------------------ #

    def shutdown(self) -> None:
        """Called on system termination (no reference analogue; ours)."""

    def on_crash(self) -> None:
        """Called by the fabric when this node is crash-injected: the
        engine must stop acting immediately (no further collector
        broadcasts), simulating an abrupt process death."""
        self.shutdown()
