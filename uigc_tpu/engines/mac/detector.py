"""MAC cycle detector with real SCC-based collection.

The reference's detector only echoes CNF probes at apparently-blocked
actors and "doesn't actually detect garbage" (reference: reference.conf:48,
mac/CycleDetector.scala:42-97).  This detector completes the algorithm:

1. Blocked actors send BLK snapshots carrying their reference count, their
   weight table, and their child count (the protocol channel mirrors
   reference: CycleDetector.scala:16-39, extended with rc/children).
2. The detector finds strongly connected components among blocked,
   childless actors and checks each candidate cycle is *closed*: every
   member's rc is exactly the sum of weights held by members toward it —
   no external actor can ever message the cycle.
3. Closed cycles are probed with CNF(token); members still blocked ACK
   (reference protocol, CycleDetector.scala:63-81).  Because in-process
   enqueue order is causal here (single node, like the reference's
   causal-delivery requirement), an app message racing the probe always
   lands before the CNF and triggers UNB, invalidating the token.
4. Fully ACKed cycles are garbage: members receive KillMsg.

Cycles containing actors with children are left uncollected (killing a
parent cascades to children the detector can't reason about) — sound but
deliberately incomplete, like the reference's supervisor marking
(ShadowGraph.java:242-267).
"""

from __future__ import annotations

import itertools
from typing import TYPE_CHECKING, Any, Dict, List, Set, Tuple

from ...runtime.behaviors import RawBehavior
from ...utils import events

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell
    from .engine import MAC


class BLK:
    """Actor has blocked (reference: CycleDetector.scala:18-23, extended
    with rc and child count for closedness checking)."""

    __slots__ = ("sender", "rc", "actor_map", "num_children")

    def __init__(self, sender, rc, actor_map, num_children):
        self.sender = sender
        self.rc = rc
        self.actor_map = actor_map  # list of (target_cell, weight)
        self.num_children = num_children


class UNB:
    """Actor unblocked after BLK (reference: CycleDetector.scala:25-29)."""

    __slots__ = ("sender",)

    def __init__(self, sender):
        self.sender = sender


class ACK:
    """Actor confirms it is still blocked (reference:
    CycleDetector.scala:31-38)."""

    __slots__ = ("sender", "token")

    def __init__(self, sender, token):
        self.sender = sender
        self.token = token


class _Wakeup:
    __slots__ = ()


WAKEUP = _Wakeup()


def strongly_connected_components(
    nodes: List[Any], edges: Dict[Any, List[Any]]
) -> List[List[Any]]:
    """Iterative Tarjan SCC over the blocked-actor graph."""
    index_of: Dict[Any, int] = {}
    lowlink: Dict[Any, int] = {}
    on_stack: Set[Any] = set()
    stack: List[Any] = []
    sccs: List[List[Any]] = []
    counter = itertools.count()

    for root in nodes:
        if root in index_of:
            continue
        work = [(root, iter(edges.get(root, ())))]
        index_of[root] = lowlink[root] = next(counter)
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index_of:
                    index_of[succ] = lowlink[succ] = next(counter)
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append((succ, iter(edges.get(succ, ()))))
                    advanced = True
                    break
                elif succ in on_stack:
                    lowlink[node] = min(lowlink[node], index_of[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index_of[node]:
                scc = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    scc.append(member)
                    if member is node:
                        break
                sccs.append(scc)
    return sccs


class CycleDetector(RawBehavior):
    """(reference: mac/CycleDetector.scala:42-97, completed)"""

    def __init__(self, engine: "MAC"):
        self.engine = engine
        self.cell: Any = None
        self.total_entries = 0
        self.total_cycles_collected = 0
        self._timer_keys: list = []
        self.device_scc_threshold = 1 << 30  # set from config in bind()
        #: blocked actors and their latest BLK snapshot
        self.blocked: Dict[Any, BLK] = {}
        #: outstanding confirmation: token -> (members, acks-received)
        self.pending: Dict[int, Tuple[Set[Any], Set[Any]]] = {}
        self._token_counter = itertools.count(1)

    def bind(self, cell: Any) -> None:
        self.cell = cell
        self.device_scc_threshold = self.engine.system.config.get_int(
            "uigc.mac.device-scc-threshold"
        )
        interval_s = self.engine.system.config.get_int("uigc.mac.wakeup-interval") / 1000.0
        key = ("mac-wakeup", id(self))
        self._timer_keys.append(key)
        self.engine.system.timers.schedule_fixed_delay(
            interval_s, lambda: cell.tell(WAKEUP), key=key
        )

    def stop_timers(self) -> None:
        for key in self._timer_keys:
            self.engine.system.timers.cancel(key)
        self._timer_keys.clear()

    def on_message(self, msg: Any) -> Any:
        if isinstance(msg, _Wakeup):
            self.scan()
        return None

    def scan(self) -> None:
        """Drain the protocol queue, then detect and confirm cycles
        (reference: CycleDetector.scala:51-89, completed)."""
        from .engine import CNF, KillMsg

        with events.recorder.timed(events.PROCESSING_MESSAGES) as ev:
            queue = self.engine.queue
            count = 0
            while True:
                try:
                    msg = queue.popleft()
                except IndexError:
                    break
                count += 1
                if isinstance(msg, BLK):
                    self.blocked[msg.sender] = msg
                elif isinstance(msg, UNB):
                    self.blocked.pop(msg.sender, None)
                    # Invalidate any pending confirmation involving it.
                    for token, (members, acks) in list(self.pending.items()):
                        if msg.sender in members:
                            del self.pending[token]
                elif isinstance(msg, ACK):
                    entry = self.pending.get(msg.token)
                    if entry is not None:
                        entry[1].add(msg.sender)
            ev.fields["num_messages"] = count
            self.total_entries += count

        # Kill fully-confirmed cycles.
        if self.engine.collect_cycles:
            for token, (members, acks) in list(self.pending.items()):
                if members <= acks and all(m in self.blocked for m in members):
                    for member in members:
                        member.tell(KillMsg)
                        self.blocked.pop(member, None)
                    del self.pending[token]
                    self.total_cycles_collected += 1

        # Detect new candidate cycles among blocked, childless actors.
        pending_members = set()
        for members, _ in self.pending.values():
            pending_members |= members
        candidates = {
            cell: blk
            for cell, blk in self.blocked.items()
            if blk.num_children == 0 and cell not in pending_members
        }
        if not candidates:
            return
        edges = {
            cell: [t for t, w in blk.actor_map if t in candidates and w > 0]
            for cell, blk in candidates.items()
        }
        if len(candidates) >= self.device_scc_threshold:
            sccs = self._device_sccs(candidates, edges)
        else:
            sccs = strongly_connected_components(list(candidates), edges)
        for scc in sccs:
            scc_set = set(scc)
            if not self._is_closed(scc_set, candidates):
                continue
            token = next(self._token_counter)
            self.pending[token] = (scc_set, set())
            for member in scc:
                member.tell(CNF(token))

    def _device_sccs(
        self, candidates: Dict[Any, Any], edges: Dict[Any, List[Any]]
    ) -> List[List[Any]]:
        """SCCs via the device kernel (ops/scc.py) for large blocked sets.

        Node and edge counts are padded to powers of two (inactive slots /
        invalid endpoints), so the jitted kernel recompiles at most
        log-many times as the blocked population grows."""
        import numpy as np

        from ...ops import scc as scc_ops

        cells = list(candidates)
        index = {cell: i for i, cell in enumerate(cells)}
        src = []
        dst = []
        for cell, targets in edges.items():
            i = index[cell]
            for t in targets:
                src.append(i)
                dst.append(index[t])

        n = len(cells)
        n_pad = 1 << max(0, (n - 1).bit_length())
        m_pad = 1 << max(0, (max(1, len(src)) - 1).bit_length())
        active = np.zeros(n_pad, dtype=bool)
        active[:n] = True
        src_a = np.full(m_pad, -1, dtype=np.int32)
        dst_a = np.full(m_pad, -1, dtype=np.int32)
        src_a[: len(src)] = src
        dst_a[: len(dst)] = dst

        labels = scc_ops.scc_labels_jax(n_pad, src_a, dst_a, active)
        groups: Dict[int, List[Any]] = {}
        for i, cell in enumerate(cells):
            groups.setdefault(int(labels[i]), []).append(cell)
        return list(groups.values())

    def _is_closed(self, scc: Set[Any], candidates: Dict[Any, BLK]) -> bool:
        """A cycle is closed iff for every member, rc + RC_INC equals the
        total weight held by members toward it (the initial self-map entry
        carries RC_INC weight that is never counted in rc — reference:
        MAC.scala:118-120).  Equality means no external actor holds a
        reference and no Inc/Dec control messages are in flight, so nothing
        outside the cycle can ever message it."""
        from .engine import RC_INC

        for member in scc:
            inbound = 0
            for owner in scc:
                for target, weight in candidates[owner].actor_map:
                    if target is member:
                        inbound += weight
            if candidates[member].rc + RC_INC != inbound:
                return False
        return True


__all__ = ["ACK", "BLK", "CycleDetector", "UNB", "strongly_connected_components"]
