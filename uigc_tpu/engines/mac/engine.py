"""The MAC engine: Pony-style weighted reference counting.

Mirrors the reference's MAC engine (reference: mac/MAC.scala:14-304):
acyclic garbage is collected by weighted reference counts (weights split
on ref creation, returned by DecMsg on release, topped up by IncMsg when
a weight can't be split), self-message balances, and child tracking via
watch/Terminated.  Requires causal delivery, hence single-node only —
like the reference (README.md:32-40).

The cycle detector (detector.py) goes beyond the reference's stub
(reference.conf:48 "the cycle detector doesn't actually detect garbage"):
it runs SCC detection over blocked-actor snapshots and collects confirmed
closed cycles.
"""

from __future__ import annotations

from collections import deque
from typing import TYPE_CHECKING, Any, Callable, Dict, Iterable, Optional, Tuple

from ...interfaces import GCMessage, Refob, SpawnInfo
from ..engine import Engine, TerminationDecision

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell
    from ...runtime.context import ActorContext

RC_INC = 255  # (reference: MAC.scala:17)


class MacRefob(Refob):
    """(reference: MAC.scala:19-22)"""

    __slots__ = ("_target",)

    def __init__(self, target: "ActorCell"):
        self._target = target

    @property
    def target(self) -> "ActorCell":
        return self._target

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, MacRefob) and self._target is other._target

    def __hash__(self) -> int:
        return hash(id(self._target))

    def __repr__(self) -> str:
        return f"MacRefob({self._target.path})"


class MacAppMsg(GCMessage):
    """(reference: MAC.scala:30-31)"""

    __slots__ = ("payload", "_refs", "is_self_msg", "external", "trace_ctx")

    def __init__(
        self,
        payload: Any,
        refs: Iterable[Refob],
        is_self_msg: bool,
        external: bool = False,
    ):
        self.payload = payload
        self._refs = tuple(refs)
        self.is_self_msg = is_self_msg
        #: wrapped by the root adapter (sent by unmanaged code): carries
        #: no sender-side accounting, so observation taps skip it.
        self.external = external
        #: causal-tracing context (uigc_tpu/telemetry/tracing.py).
        self.trace_ctx = None

    @property
    def refs(self) -> Tuple[Refob, ...]:
        return self._refs


class DecMsg(GCMessage):
    """(reference: MAC.scala:33-35)"""

    __slots__ = ("weight",)

    def __init__(self, weight: int):
        self.weight = weight

    @property
    def refs(self):
        return ()


class _IncMsg(GCMessage):
    """(reference: MAC.scala:37-39)"""

    __slots__ = ()

    @property
    def refs(self):
        return ()


IncMsg = _IncMsg()


class CNF(GCMessage):
    """Cycle-detector confirmation probe (reference: MAC.scala:41-48)."""

    __slots__ = ("token",)

    def __init__(self, token: int):
        self.token = token

    @property
    def refs(self):
        return ()


class _KillMsg(GCMessage):
    """Kill order for a confirmed garbage cycle (ours; the reference's
    detector never collects — reference.conf:48)."""

    __slots__ = ()

    @property
    def refs(self):
        return ()


KillMsg = _KillMsg()


class Pair:
    """(reference: MAC.scala:65-68)"""

    __slots__ = ("num_refs", "weight")

    def __init__(self, num_refs: int = 0, weight: int = 0):
        self.num_refs = num_refs
        self.weight = weight


class MacSpawnInfo(SpawnInfo):
    __slots__ = ("is_root",)

    def __init__(self, is_root: bool):
        self.is_root = is_root


class MacState:
    """(reference: MAC.scala:54-63)"""

    __slots__ = (
        "self_ref",
        "is_root",
        "actor_map",
        "rc",
        "pending_self_messages",
        "has_sent_blk",
        "app_msg_count",
        "ctrl_msg_count",
    )

    def __init__(self, self_ref: MacRefob, is_root: bool):
        self.self_ref = self_ref
        self.is_root = is_root
        self.actor_map: Dict["ActorCell", Pair] = {}
        self.rc = RC_INC
        self.pending_self_messages = 0
        self.has_sent_blk = False
        self.app_msg_count = 0
        self.ctrl_msg_count = 0


class MAC(Engine):
    """(reference: mac/MAC.scala:76-304)"""

    def __init__(self, system: Any):
        super().__init__(system)
        config = system.config
        self.cycle_detection = config.get_bool("uigc.mac.cycle-detection")
        self.collect_cycles = config.get_bool("uigc.mac.collect-cycles")
        # BLK/UNB/ACK channel to the detector (reference: MAC.scala:89).
        self.queue: deque = deque()
        self.detector = None
        self.detector_cell = None
        if self.cycle_detection:
            from .detector import CycleDetector

            self.detector = CycleDetector(self)
            self.detector_cell = system.spawn_system_raw(
                self.detector, "CycleDetector", pinned=True
            )

    # -- Root support -------------------------------------------------- #

    def root_message(self, payload: Any, refs: Iterable[Refob]) -> GCMessage:
        return MacAppMsg(payload, refs, is_self_msg=False, external=True)

    def root_spawn_info(self) -> SpawnInfo:
        return MacSpawnInfo(is_root=True)

    def to_root_refob(self, cell: "ActorCell") -> Refob:
        return MacRefob(cell)

    # -- Lifecycle ----------------------------------------------------- #

    def init_state(self, cell: "ActorCell", spawn_info: MacSpawnInfo) -> MacState:
        """(reference: MAC.scala:114-147)"""
        state = MacState(MacRefob(cell), spawn_info.is_root)
        state.actor_map[cell] = Pair(num_refs=1, weight=RC_INC)

        if self.cycle_detection:
            from .detector import BLK

            def on_block() -> None:
                if not state.has_sent_blk:
                    snapshot = [
                        (target, pair.weight)
                        for target, pair in state.actor_map.items()
                    ]
                    self.queue.append(
                        BLK(
                            cell,
                            state.rc,
                            snapshot,
                            num_children=len(cell.children),
                        )
                    )
                    state.has_sent_blk = True

            cell.on_finished_processing = on_block
        return state

    def get_self_ref(self, state: MacState, cell: "ActorCell") -> Refob:
        return state.self_ref

    def spawn(
        self, factory: Callable[[SpawnInfo], "ActorCell"], state: MacState, ctx: "ActorContext"
    ) -> Refob:
        """(reference: MAC.scala:155-166)"""
        child = factory(MacSpawnInfo(is_root=False))
        ctx.cell.watch(child)
        state.actor_map[child] = Pair(num_refs=1, weight=RC_INC)
        return MacRefob(child)

    # -- Message path -------------------------------------------------- #

    def _unblocked(self, state: MacState, cell: "ActorCell") -> None:
        """(reference: MAC.scala:168-173)"""
        if self.cycle_detection and state.has_sent_blk:
            from .detector import UNB

            state.has_sent_blk = False
            self.queue.append(UNB(cell))

    def send_message(
        self, ref: MacRefob, msg: Any, refs: Iterable[Refob], state: MacState, ctx: "ActorContext"
    ) -> None:
        """(reference: MAC.scala:290-303)"""
        is_self_msg = ref.target is state.self_ref.target
        if is_self_msg:
            state.pending_self_messages += 1
        if self.tap is not None:
            self.tap.on_send(ref.target)
        app_msg = MacAppMsg(msg, refs, is_self_msg)
        tel = self.system.telemetry
        if tel is not None and tel.tracer.enabled:
            app_msg.trace_ctx = tel.tracer.on_send(
                target=ref.target.path, uid=ref.target.uid
            )
        ref.target.tell(app_msg)

    def on_message(
        self, msg: GCMessage, state: MacState, ctx: "ActorContext"
    ) -> Optional[Any]:
        """(reference: MAC.scala:175-210)"""
        cell = ctx.cell
        if isinstance(msg, MacAppMsg):
            if self.tap is not None and not msg.external:
                self.tap.on_recv(cell)
            self._unblocked(state, cell)
            state.app_msg_count += 1
            if msg.is_self_msg:
                state.pending_self_messages -= 1
            for ref in msg.refs:
                pair = state.actor_map.get(ref.target)
                if pair is None:
                    pair = Pair()
                    state.actor_map[ref.target] = pair
                pair.num_refs += 1
                pair.weight += 1
            return msg.payload
        if isinstance(msg, DecMsg):
            self._unblocked(state, cell)
            state.ctrl_msg_count += 1
            state.rc -= msg.weight
            return None
        if isinstance(msg, _IncMsg):
            self._unblocked(state, cell)
            state.ctrl_msg_count += 1
            state.rc += RC_INC
            return None
        if isinstance(msg, CNF):
            state.ctrl_msg_count += 1
            if self.cycle_detection and state.has_sent_blk:
                from .detector import ACK

                self.queue.append(ACK(cell, msg.token))
            return None
        if isinstance(msg, _KillMsg):
            return None
        return None

    def on_idle(
        self, msg: GCMessage, state: MacState, ctx: "ActorContext"
    ) -> TerminationDecision:
        """(reference: MAC.scala:212-217)"""
        if isinstance(msg, _KillMsg):
            return TerminationDecision.SHOULD_STOP
        return self.try_terminate(state, ctx)

    def post_signal(
        self, signal: Any, state: MacState, ctx: "ActorContext"
    ) -> TerminationDecision:
        """(reference: MAC.scala:225-235)"""
        from ...runtime.signals import Terminated

        if isinstance(signal, Terminated):
            return self.try_terminate(state, ctx)
        return TerminationDecision.UNHANDLED

    def try_terminate(
        self, state: MacState, ctx: "ActorContext"
    ) -> TerminationDecision:
        """(reference: MAC.scala:237-246)"""
        if (
            not state.is_root
            and state.rc == 0
            and state.pending_self_messages == 0
            and not ctx.cell.children
        ):
            return TerminationDecision.SHOULD_STOP
        return TerminationDecision.SHOULD_CONTINUE

    # -- Reference management ------------------------------------------ #

    def create_ref(
        self, target: MacRefob, owner: Refob, state: MacState, ctx: "ActorContext"
    ) -> Refob:
        """Weight splitting (reference: MAC.scala:248-266)."""
        if self.tap is not None:
            self.tap.on_create(owner.target, target.target)
        if target.target is ctx.cell:
            state.rc += 1
            return MacRefob(target.target)
        pair = state.actor_map[target.target]
        if pair.weight <= 1:
            pair.weight += RC_INC - 1
            target.target.tell(IncMsg)
        else:
            pair.weight -= 1
        return MacRefob(target.target)

    def release(
        self, releasing: Iterable[MacRefob], state: MacState, ctx: "ActorContext"
    ) -> None:
        """(reference: MAC.scala:268-288)"""
        tap = self.tap
        dec_sends = []
        for ref in releasing:
            if tap is not None:
                tap.on_release(
                    ref,
                    already_released=(
                        ref.target is not ctx.cell
                        and ref.target not in state.actor_map
                    ),
                )
            if ref.target is ctx.cell:
                state.rc -= 1
            else:
                pair = state.actor_map[ref.target]
                if pair.num_refs <= 1:
                    dec_sends.append((ref.target, DecMsg(pair.weight)))
                    del state.actor_map[ref.target]
                else:
                    pair.num_refs -= 1
        if len(dec_sends) > 1:
            # Bulk decrement fan-out: one dispatcher submission per
            # dispatcher for the whole release set (runtime/cell.py).
            from ...runtime.cell import tell_bulk

            tell_bulk(dec_sends)
        else:
            for target_cell, dec in dec_sends:
                target_cell.tell(dec)

    # -- Shutdown ------------------------------------------------------ #

    def shutdown(self) -> None:
        if self.detector is not None:
            self.detector.stop_timers()
