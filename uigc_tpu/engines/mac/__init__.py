from .detector import CycleDetector, strongly_connected_components
from .engine import MAC, MacRefob, MacState, RC_INC

__all__ = [
    "CycleDetector",
    "MAC",
    "MacRefob",
    "MacState",
    "RC_INC",
    "strongly_connected_components",
]
