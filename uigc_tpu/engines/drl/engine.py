"""The DRL engine: Plyukhin–Agha distributed reference listing.

Mirrors the reference's DRL engine (reference: drl/DRL.scala:17-161,
drl/State.scala:7-284, drl/GCMessage.scala, drl/Refob.scala): every refob
carries a globally unique token; owners maintain active-ref sets, targets
maintain owner sets; releases travel as ReleaseMsg carrying both the
released refs and the refs created using them (two-phase owner
reconciliation); per-token send/receive counts detect in-flight messages;
termination when no children, no nontrivial inverse acquaintances (Chain
Lemma), and no pending self-messages.

Unlike the reference — where DRL exists but is not selectable
(UIGC.scala:14-18 has no "drl" case) — this engine is wired into the
registry under ``uigc.engine = "drl"``.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Callable, Dict, Iterable, List, Optional, Tuple

from ...interfaces import GCMessage, Refob, SpawnInfo
from ..engine import Engine, TerminationDecision

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell
    from ...runtime.context import ActorContext


class Token:
    """An opaque, globally unique token (reference: drl/Refob.scala:7-9)."""

    __slots__ = ("ref", "n")

    def __init__(self, ref: "ActorCell", n: int):
        self.ref = ref
        self.n = n

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Token) and self.ref is other.ref and self.n == other.n

    def __hash__(self) -> int:
        return hash((id(self.ref), self.n))

    def __repr__(self) -> str:
        return f"Token({self.ref.path},{self.n})"


class DrlRefob(Refob):
    """(reference: drl/Refob.scala:11-17)"""

    __slots__ = ("token", "owner", "_target")

    def __init__(self, token: Optional[Token], owner: Optional["ActorCell"], target: "ActorCell"):
        self.token = token
        self.owner = owner
        self._target = target

    @property
    def target(self) -> "ActorCell":
        return self._target

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, DrlRefob)
            and self.token == other.token
            and self.owner is other.owner
            and self._target is other._target
        )

    def __hash__(self) -> int:
        return hash((self.token, id(self.owner), id(self._target)))

    def __repr__(self) -> str:
        return f"DrlRefob({self.token},{self._target.path})"


class DrlAppMsg(GCMessage):
    """(reference: drl/GCMessage.scala:7-11)"""

    __slots__ = ("payload", "token", "_refs", "trace_ctx")

    def __init__(self, payload: Any, token: Optional[Token], refs: Iterable[Refob]):
        self.payload = payload
        self.token = token
        self._refs = tuple(refs)
        #: causal-tracing context (uigc_tpu/telemetry/tracing.py).
        self.trace_ctx = None

    @property
    def refs(self) -> Tuple[Refob, ...]:
        return self._refs


class ReleaseMsg(GCMessage):
    """(reference: drl/GCMessage.scala:13-17)"""

    __slots__ = ("releasing", "created")

    def __init__(self, releasing: Iterable[DrlRefob], created: Iterable[DrlRefob]):
        self.releasing = tuple(releasing)
        self.created = tuple(created)

    @property
    def refs(self):
        return ()


class _SelfCheck(GCMessage):
    """(reference: drl/GCMessage.scala:21-24)"""

    __slots__ = ()

    @property
    def refs(self):
        return ()


SelfCheck = _SelfCheck()


class DrlSpawnInfo(SpawnInfo):
    """(reference: drl/DRL.scala:11-14)"""

    __slots__ = ("token", "creator")

    def __init__(self, token: Optional[Token], creator: Optional["ActorCell"]):
        self.token = token
        self.creator = creator


class DrlState:
    """(reference: drl/State.scala:7-284)"""

    __slots__ = (
        "self_cell",
        "count",
        "self_ref",
        "active_refs",
        "created_using",
        "owners",
        "released_owners",
        "sent_count",
        "recv_count",
        "pending_self_releases",
    )

    def __init__(self, cell: "ActorCell", spawn_info: DrlSpawnInfo):
        self.self_cell = cell
        self.count = 1
        self.self_ref = DrlRefob(Token(cell, 0), cell, cell)
        creator_ref = DrlRefob(spawn_info.token, spawn_info.creator, cell)
        self.active_refs: List[DrlRefob] = [self.self_ref]
        self.created_using: Dict[DrlRefob, List[DrlRefob]] = {}
        self.owners: List[DrlRefob] = [self.self_ref, creator_ref]
        self.released_owners: List[DrlRefob] = []
        self.sent_count: Dict[Token, int] = {self.self_ref.token: 0}
        self.recv_count: Dict[Token, int] = {self.self_ref.token: 0}
        self.pending_self_releases = 0

    def new_token(self) -> Token:
        token = Token(self.self_cell, self.count)
        self.count += 1
        return token

    def trivial_active_refs(self) -> List[DrlRefob]:
        return [r for r in self.active_refs if r.target is self.self_cell]

    def nontrivial_active_refs(self) -> List[DrlRefob]:
        return [r for r in self.active_refs if r.target is not self.self_cell]

    def handle_message(self, refs: Iterable[DrlRefob], token: Optional[Token]) -> None:
        """(reference: drl/State.scala:66-69)"""
        self.active_refs.extend(refs)
        self.inc_received(token)

    def handle_release(self, releasing: Tuple[DrlRefob, ...], created: Tuple[DrlRefob, ...]) -> None:
        """Two-phase owner reconciliation (reference: drl/State.scala:75-104)."""
        assert releasing
        sender = releasing[0].owner
        if sender is self.self_cell:
            self.pending_self_releases -= 1
        for ref in releasing:
            self.recv_count.pop(ref.token, None)
            if ref in self.owners:
                self.owners.remove(ref)
            else:
                self.released_owners.append(ref)
        for ref in created:
            if ref in self.released_owners:
                self.released_owners.remove(ref)
            else:
                self.owners.append(ref)

    def handle_self_check(self) -> None:
        self.inc_received(self.self_ref.token)

    def any_pending_self_messages(self) -> bool:
        """(reference: drl/State.scala:118-150)"""
        if self.pending_self_releases > 0:
            return True
        for ref in self.trivial_active_refs():
            token = ref.token
            if token in self.sent_count:
                if token not in self.recv_count:
                    return True
                assert self.sent_count[token] >= self.recv_count[token]
                if self.sent_count[token] > self.recv_count[token]:
                    return True
        return False

    def any_inverse_acquaintances(self) -> bool:
        """Chain Lemma check (reference: drl/State.scala:155-164)."""
        for ref in self.owners:
            if ref.owner is None or ref.owner is not self.self_cell:
                return True
        return False

    def handle_created_ref(self, target: DrlRefob, new_ref: DrlRefob) -> None:
        """(reference: drl/State.scala:166-189)"""
        assert target.target is new_ref.target
        assert target in self.active_refs
        if target.target is self.self_cell:
            self.owners.append(new_ref)
        else:
            self.created_using.setdefault(target, []).append(new_ref)

    def release(self, releasing: Iterable[DrlRefob]):
        """Group releases by target (reference: drl/State.scala:197-239).
        Returns {target_cell: (released refs, created refs)}."""
        targets: Dict["ActorCell", Tuple[List[DrlRefob], List[DrlRefob]]] = {}
        releasing = list(releasing)
        nontrivial = self.nontrivial_active_refs()
        for ref in releasing:
            if ref not in nontrivial:
                continue
            self.sent_count.pop(ref.token, None)
            key = ref.target
            released, created = targets.setdefault(key, ([], []))
            released.append(ref)
            created.extend(self.created_using.pop(ref, []))
            self.active_refs.remove(ref)

        trivial = self.trivial_active_refs()
        refs_to_self: List[DrlRefob] = []
        for ref in releasing:
            if ref in trivial and ref != self.self_ref:
                self.sent_count.pop(ref.token, None)
                self.active_refs.remove(ref)
                refs_to_self.append(ref)
        if refs_to_self:
            targets[self.self_cell] = (refs_to_self, [])
            self.pending_self_releases += 1
        return targets

    def inc_received(self, token: Optional[Token]) -> None:
        if token is not None:
            self.recv_count[token] = self.recv_count.get(token, 0) + 1

    def inc_sent(self, token: Optional[Token]) -> None:
        if token is not None:
            self.sent_count[token] = self.sent_count.get(token, 0) + 1


class DRL(Engine):
    """(reference: drl/DRL.scala:17-161)"""

    def root_message(self, payload: Any, refs: Iterable[Refob]) -> GCMessage:
        return DrlAppMsg(payload, None, refs)

    def root_spawn_info(self) -> SpawnInfo:
        return DrlSpawnInfo(None, None)

    def to_root_refob(self, cell: "ActorCell") -> Refob:
        return DrlRefob(None, None, cell)

    def init_state(self, cell: "ActorCell", spawn_info: DrlSpawnInfo) -> DrlState:
        return DrlState(cell, spawn_info)

    def get_self_ref(self, state: DrlState, cell: "ActorCell") -> Refob:
        return state.self_ref

    def spawn(
        self, factory: Callable[[SpawnInfo], "ActorCell"], state: DrlState, ctx: "ActorContext"
    ) -> Refob:
        """(reference: drl/DRL.scala:48-60)"""
        token = state.new_token()
        child = factory(DrlSpawnInfo(token, state.self_cell))
        ref = DrlRefob(token, state.self_cell, child)
        state.active_refs.append(ref)
        ctx.cell.watch(child)
        return ref

    def send_message(
        self, ref: DrlRefob, msg: Any, refs: Iterable[Refob], state: DrlState, ctx: "ActorContext"
    ) -> None:
        """(reference: drl/DRL.scala:148-160)"""
        if self.tap is not None:
            self.tap.on_send(ref.target)
        app_msg = DrlAppMsg(msg, ref.token, refs)
        tel = self.system.telemetry
        if tel is not None and tel.tracer.enabled:
            app_msg.trace_ctx = tel.tracer.on_send(
                target=ref.target.path, uid=ref.target.uid
            )
        ref.target.tell(app_msg)
        state.inc_sent(ref.token)

    def on_message(
        self, msg: GCMessage, state: DrlState, ctx: "ActorContext"
    ) -> Optional[Any]:
        """(reference: drl/DRL.scala:62-88)"""
        if isinstance(msg, DrlAppMsg):
            # token None marks the root adapter's external wrap: no
            # sender-side accounting exists for it, so the tap skips it.
            if self.tap is not None and msg.token is not None:
                self.tap.on_recv(ctx.cell)
            state.handle_message(msg.refs, msg.token)
            return msg.payload
        if isinstance(msg, ReleaseMsg):
            state.handle_release(msg.releasing, msg.created)
            return None
        if isinstance(msg, _SelfCheck):
            state.handle_self_check()
            return None
        return None

    def on_idle(
        self, msg: GCMessage, state: DrlState, ctx: "ActorContext"
    ) -> TerminationDecision:
        """(reference: drl/DRL.scala:90-106)"""
        return self.try_terminate(state, ctx)

    def try_terminate(self, state: DrlState, ctx: "ActorContext") -> TerminationDecision:
        if (
            ctx.cell.children
            or state.any_inverse_acquaintances()
            or state.any_pending_self_messages()
        ):
            return TerminationDecision.SHOULD_CONTINUE
        return TerminationDecision.SHOULD_STOP

    def post_signal(
        self, signal: Any, state: DrlState, ctx: "ActorContext"
    ) -> TerminationDecision:
        from ...runtime.signals import Terminated

        if isinstance(signal, Terminated):
            return self.try_terminate(state, ctx)
        return TerminationDecision.UNHANDLED

    def create_ref(
        self, target: DrlRefob, owner: DrlRefob, state: DrlState, ctx: "ActorContext"
    ) -> Refob:
        """(reference: drl/DRL.scala:108-118)"""
        if self.tap is not None:
            self.tap.on_create(owner.target, target.target)
        token = state.new_token()
        ref = DrlRefob(token, owner.target, target.target)
        state.handle_created_ref(target, ref)
        return ref

    def release(
        self, releasing: Iterable[DrlRefob], state: DrlState, ctx: "ActorContext"
    ) -> None:
        """(reference: drl/DRL.scala:120-132)"""
        releasing = list(releasing)
        tap = self.tap
        if tap is not None:
            for ref in releasing:
                tap.on_release(
                    ref, already_released=ref not in state.active_refs
                )
        targets = state.release(releasing)
        if len(targets) > 1:
            # Bulk release: one dispatcher submission per dispatcher for
            # the whole target set (runtime/cell.py tell_bulk), so a
            # wide release fan-out costs O(batches), not O(targets).
            from ...runtime.cell import tell_bulk

            tell_bulk(
                (target_cell, ReleaseMsg(released, created))
                for target_cell, (released, created) in targets.items()
            )
        else:
            for target_cell, (released, created) in targets.items():
                target_cell.tell(ReleaseMsg(released, created))
