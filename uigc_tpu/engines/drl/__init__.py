from .engine import DRL, DrlRefob, DrlState, Token

__all__ = ["DRL", "DrlRefob", "DrlState", "Token"]
