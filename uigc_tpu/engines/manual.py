"""The no-op engine: GC off, every hook is the identity.

Mirrors the reference's ``Manual`` engine (reference:
src/main/scala/edu/illinois/osl/uigc/engines/Manual.scala:26-116) — the
SPI's minimal conformance example.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

from ..interfaces import GCMessage, Refob, SpawnInfo, State
from .engine import Engine, TerminationDecision


class ManualSpawnInfo(SpawnInfo):
    __slots__ = ()


class ManualGCMessage(GCMessage):
    """(reference: Manual.scala:10-11)"""

    __slots__ = ("payload", "_refs")

    def __init__(self, payload: Any, refs: Iterable[Refob]):
        self.payload = payload
        self._refs = tuple(refs)

    @property
    def refs(self) -> Iterable[Refob]:
        return self._refs


class ManualRefob(Refob):
    """(reference: Manual.scala:13-16)"""

    __slots__ = ("_target",)

    def __init__(self, target: Any):
        self._target = target

    @property
    def target(self) -> Any:
        return self._target

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ManualRefob) and self._target is other._target

    def __hash__(self) -> int:
        return hash(id(self._target))

    def __repr__(self) -> str:
        return f"ManualRefob({self._target.path})"


class ManualState(State):
    __slots__ = ("self_ref",)

    def __init__(self, self_ref: ManualRefob):
        self.self_ref = self_ref


class Manual(Engine):
    """GC disabled; all hooks are identity/ShouldContinue
    (reference: Manual.scala:26-116)."""

    def root_message(self, payload: Any, refs: Iterable[Refob]) -> GCMessage:
        return ManualGCMessage(payload, refs)

    def root_spawn_info(self) -> SpawnInfo:
        return ManualSpawnInfo()

    def to_root_refob(self, cell: Any) -> Refob:
        return ManualRefob(cell)

    def init_state(self, cell: Any, spawn_info: SpawnInfo) -> State:
        return ManualState(ManualRefob(cell))

    def get_self_ref(self, state: ManualState, cell: Any) -> Refob:
        return state.self_ref

    def spawn(self, factory: Callable, state: State, ctx: Any) -> Refob:
        return ManualRefob(factory(ManualSpawnInfo()))

    def send_message(self, ref: ManualRefob, msg: Any, refs: Iterable[Refob], state: State, ctx: Any) -> None:
        ref.target.tell(ManualGCMessage(msg, refs))

    def on_message(self, msg: ManualGCMessage, state: State, ctx: Any) -> Optional[Any]:
        return msg.payload

    def on_idle(self, msg: GCMessage, state: State, ctx: Any) -> TerminationDecision:
        return TerminationDecision.SHOULD_CONTINUE

    def create_ref(self, target: ManualRefob, owner: Refob, state: State, ctx: Any) -> Refob:
        return ManualRefob(target.target)

    def release(self, releasing: Iterable[Refob], state: State, ctx: Any) -> None:
        return None
