"""Undo logs: reverting a dead node's unadmitted effects.

Mirrors the reference's UndoLog (reference: crgc/UndoLog.java:16-105):
per remote node, subtract everything that node *claimed* to have sent or
created toward actors it did not host (mergeDeltaGraph), and add back
what provably crossed each link (mergeIngressEntry).  Once every
surviving peer's final ingress entry has arrived (the finalization
quorum, reference: LocalGC.scala:253-257), the net log is folded into the
shadow graph: the dead node's actors halt and its unadmitted sends/refs
are reverted (reference: ShadowGraph.java:158-174).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Dict, Set

from ...utils import events
from .delta import DeltaGraph
from .gateways import IngressEntry

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell


class UndoLogField:
    """(reference: UndoLog.java:21-31)"""

    __slots__ = ("message_count", "created_refs")

    def __init__(self) -> None:
        self.message_count = 0
        self.created_refs: Dict["ActorCell", int] = {}


class UndoLog:
    """(reference: UndoLog.java:16-105)

    Fence discipline (ours): windows are keyed by (peer, fence era) at
    the ingress, and the log refuses pre-death stragglers of a rejoined
    incarnation — ``fence`` floors entries tallied by *this* node
    (whose eras we know exactly: it is set to our current era for the
    address when the log is created at rejoin), per-ingress floors
    seeded from the superseded log (:meth:`seed_floors`) fence out
    every era a peer already used for the dead incarnation, and
    per-ingress monotonicity covers the rest of each peer's rebroadcast
    stream without ever comparing fence counters across nodes (a late
    joiner legitimately counts fewer deaths than a veteran).  The one
    ordering none of those can judge — a peer whose FIRST entry after a
    rejoin is a dead-era straggler, with no floor on record — has two
    guards.  Primary: ``expected_nonce``, the process-incarnation
    identity (hello nonce) of the incarnation this log covers — every
    observer stamps the SAME value, so a straggler about a previous
    incarnation is refused outright before it can tally or join the
    fold quorum, with no counter comparison at all.  Fallback (nonce
    unknown: in-process fabrics, old peers): era supersession — tallies
    are bucketed per (ingress, fence), and a higher-era entry from the
    same ingress un-applies the lower era's tallies and withdraws its
    finalization before merging."""

    def __init__(
        self,
        node_address: str,
        fence: int = 0,
        own_address: "str | None" = None,
        expected_nonce: int = 0,
    ):
        self.node_address = node_address
        self.finalized_by: Set[str] = set()
        self.admitted: Dict["ActorCell", UndoLogField] = {}
        self.fence = fence
        self.own_address = own_address
        self.expected_nonce = expected_nonce
        #: highest fence seen per ingress address — a dip within one
        #: observer's stream is a pre-death straggler, dropped
        self._ingress_fences: Dict[str, int] = {}
        #: minimum acceptable era per ingress address, seeded at rejoin
        #: from the superseded incarnation's log
        self._ingress_floors: Dict[str, int] = {}
        #: era whose tallies are currently merged, per ingress, plus
        #: the NET of those tallies (kept so supersession can invert
        #: without retaining entry objects: the aggregate is bounded by
        #: the actors the stream touched, not by window count) and how
        #: many windows fed it (diagnostics only)
        self._applied_eras: Dict[str, int] = {}
        self._applied_net: Dict[str, Dict[Any, UndoLogField]] = {}
        self._applied_counts: Dict[str, int] = {}

    def _field(self, cell: "ActorCell") -> UndoLogField:
        field = self.admitted.get(cell)
        if field is None:
            field = UndoLogField()
            self.admitted[cell] = field
        return field

    def merge_delta_graph(self, delta: DeltaGraph) -> None:
        """Subtract the dead node's claims toward non-interned (remote)
        actors (reference: UndoLog.java:39-67)."""
        decoder = delta.decoder()
        for i, shadow in enumerate(delta.shadows):
            if shadow.interned:
                # Only sends/creates toward actors on OTHER nodes matter.
                continue
            field = self._field(decoder[i])
            field.message_count -= shadow.recv_count
            for target_id, count in shadow.outgoing.items():
                target = decoder[target_id]
                self._update(field.created_refs, target, -count)

    def stale_fence(self, entry: IngressEntry) -> bool:
        """True when the entry belongs to a window era this log must
        not merge (its stream pre-dates a rejoin this log post-dates).
        Checked — and the per-stream watermark advanced — before any
        tally lands."""
        src = entry.ingress_address
        if src is None:
            return False
        if (
            self.expected_nonce
            and entry.nonce
            and entry.nonce != self.expected_nonce
        ):
            # The entry tallies a DIFFERENT incarnation of the address
            # than the one this log covers — the exact, observer-
            # independent verdict (no era inference needed).
            return True
        if src == self.own_address and entry.fence < self.fence:
            return True
        if entry.fence < self._ingress_floors.get(src, 0):
            return True
        seen = self._ingress_fences.get(src)
        if seen is not None and entry.fence < seen:
            return True
        self._ingress_fences[src] = entry.fence
        return False

    def seed_floors(self, prior: "UndoLog") -> None:
        """Carry the superseded incarnation's per-ingress knowledge
        into the rejoined incarnation's log: any era a peer used toward
        the dead stream is below that peer's era for the live one, so
        the common straggler ordering — a dead-era rebroadcast arriving
        first after the rejoin — is refused outright instead of waiting
        for supersession."""
        for src, era in prior._ingress_fences.items():
            self._ingress_floors[src] = max(
                self._ingress_floors.get(src, 0), era + 1,
            )
        for src, floor in prior._ingress_floors.items():
            self._ingress_floors[src] = max(
                self._ingress_floors.get(src, 0), floor,
            )

    def _discard_superseded(self, src: str) -> None:
        """A higher-era entry from ``src`` proves the tallies currently
        merged for it belong to the dead incarnation's stream (the
        no-floor first-straggler ordering): un-apply their net and
        withdraw any finalization they granted — a stale final must
        never satisfy the fold quorum."""
        stale_net = self._applied_net.pop(src, {})
        for cell, net in stale_net.items():
            field = self._field(cell)
            # Application subtracted the raw admitted counts and added
            # the raw created refs; inversion does the opposite.
            field.message_count += net.message_count
            for target, count in net.created_refs.items():
                self._update(field.created_refs, target, -count)
            self._drop_if_zero(cell, field)
        self.finalized_by.discard(src)
        events.recorder.commit(
            events.STALE_WINDOW,
            peer=self.node_address,
            ingress=src,
            fence=self._applied_eras.get(src, 0),
            log_fence=self.fence,
            superseded=self._applied_counts.pop(src, 0),
        )

    def merge_ingress_entry(self, entry: IngressEntry) -> None:
        """Cancel the admitted portion of the dead node's claims
        (reference: UndoLog.java:69-93).

        Sign note — deliberate deviation: sends enter the shadow graph
        NEGATIVELY (recv_count -= send_count) while created refs enter
        POSITIVELY, so reverting unadmitted claims requires
        ``message_count = claimed - admitted`` (applied as +) but
        ``created_refs = admitted - claimed`` (applied as +).  The
        reference adds admitted message counts (UndoLog.java:81), which
        would leave every fully-admitted message double-counted in the
        receive balance after the undo, pinning the recipient as a
        pseudoroot forever; we subtract instead."""
        src = entry.ingress_address
        net = None
        if src is not None and src != self.own_address:
            era = self._applied_eras.get(src)
            if era is not None and entry.fence > era:
                self._discard_superseded(src)
            self._applied_eras[src] = entry.fence
            if entry.admitted:
                net = self._applied_net.setdefault(src, {})
                self._applied_counts[src] = self._applied_counts.get(src, 0) + 1
        for cell, entry_field in entry.admitted.items():
            field = self._field(cell)
            field.message_count -= entry_field.message_count
            for target, count in entry_field.created_refs.items():
                self._update(field.created_refs, target, count)
            self._drop_if_zero(cell, field)
            if net is not None:
                nf = net.get(cell)
                if nf is None:
                    nf = net[cell] = UndoLogField()
                nf.message_count += entry_field.message_count
                for target, count in entry_field.created_refs.items():
                    self._update(nf.created_refs, target, count)
        if entry.is_final:
            self.finalized_by.add(entry.ingress_address)

    def _drop_if_zero(self, cell: Any, field: UndoLogField) -> None:
        # A net-zero field is indistinguishable from an absent one to
        # every merge_undo_log consumer; dropping it keeps summary()
        # honest after a supersession.
        if not field.message_count and not field.created_refs:
            self.admitted.pop(cell, None)

    def summary(self) -> Dict[str, int]:
        """Structured size of the net log (event fields for the
        ``crgc.undo_fold`` commit and the chaos bench): how many actors
        carry a reverted message balance or reverted created refs, and
        how many surviving peers finalized."""
        return {
            "reverted_actors": len(self.admitted),
            "reverted_messages": sum(
                abs(f.message_count) for f in self.admitted.values()
            ),
            "reverted_refs": sum(
                len(f.created_refs) for f in self.admitted.values()
            ),
            "finalized_by": len(self.finalized_by),
        }

    @staticmethod
    def _update(outgoing: Dict[Any, int], target: Any, delta: int) -> None:
        count = outgoing.get(target, 0) + delta
        if count == 0:
            outgoing.pop(target, None)
        else:
            outgoing[target] = count
