"""Undo logs: reverting a dead node's unadmitted effects.

Mirrors the reference's UndoLog (reference: crgc/UndoLog.java:16-105):
per remote node, subtract everything that node *claimed* to have sent or
created toward actors it did not host (mergeDeltaGraph), and add back
what provably crossed each link (mergeIngressEntry).  Once every
surviving peer's final ingress entry has arrived (the finalization
quorum, reference: LocalGC.scala:253-257), the net log is folded into the
shadow graph: the dead node's actors halt and its unadmitted sends/refs
are reverted (reference: ShadowGraph.java:158-174).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Dict, Set

from .delta import DeltaGraph
from .gateways import IngressEntry

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell


class UndoLogField:
    """(reference: UndoLog.java:21-31)"""

    __slots__ = ("message_count", "created_refs")

    def __init__(self) -> None:
        self.message_count = 0
        self.created_refs: Dict["ActorCell", int] = {}


class UndoLog:
    """(reference: UndoLog.java:16-105)"""

    def __init__(self, node_address: str):
        self.node_address = node_address
        self.finalized_by: Set[str] = set()
        self.admitted: Dict["ActorCell", UndoLogField] = {}

    def _field(self, cell: "ActorCell") -> UndoLogField:
        field = self.admitted.get(cell)
        if field is None:
            field = UndoLogField()
            self.admitted[cell] = field
        return field

    def merge_delta_graph(self, delta: DeltaGraph) -> None:
        """Subtract the dead node's claims toward non-interned (remote)
        actors (reference: UndoLog.java:39-67)."""
        decoder = delta.decoder()
        for i, shadow in enumerate(delta.shadows):
            if shadow.interned:
                # Only sends/creates toward actors on OTHER nodes matter.
                continue
            field = self._field(decoder[i])
            field.message_count -= shadow.recv_count
            for target_id, count in shadow.outgoing.items():
                target = decoder[target_id]
                self._update(field.created_refs, target, -count)

    def merge_ingress_entry(self, entry: IngressEntry) -> None:
        """Cancel the admitted portion of the dead node's claims
        (reference: UndoLog.java:69-93).

        Sign note — deliberate deviation: sends enter the shadow graph
        NEGATIVELY (recv_count -= send_count) while created refs enter
        POSITIVELY, so reverting unadmitted claims requires
        ``message_count = claimed - admitted`` (applied as +) but
        ``created_refs = admitted - claimed`` (applied as +).  The
        reference adds admitted message counts (UndoLog.java:81), which
        would leave every fully-admitted message double-counted in the
        receive balance after the undo, pinning the recipient as a
        pseudoroot forever; we subtract instead."""
        for cell, entry_field in entry.admitted.items():
            field = self._field(cell)
            field.message_count -= entry_field.message_count
            for target, count in entry_field.created_refs.items():
                self._update(field.created_refs, target, count)
        if entry.is_final:
            self.finalized_by.add(entry.ingress_address)

    def summary(self) -> Dict[str, int]:
        """Structured size of the net log (event fields for the
        ``crgc.undo_fold`` commit and the chaos bench): how many actors
        carry a reverted message balance or reverted created refs, and
        how many surviving peers finalized."""
        return {
            "reverted_actors": len(self.admitted),
            "reverted_messages": sum(
                abs(f.message_count) for f in self.admitted.values()
            ),
            "reverted_refs": sum(
                len(f.created_refs) for f in self.admitted.values()
            ),
            "finalized_by": len(self.finalized_by),
        }

    @staticmethod
    def _update(outgoing: Dict[Any, int], target: Any, delta: int) -> None:
        count = outgoing.get(target, 0) + delta
        if count == 0:
            outgoing.pop(target, None)
        else:
            outgoing[target] = count
