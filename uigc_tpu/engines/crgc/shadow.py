"""The oracle shadow graph: pointer-based, reference-exact semantics.

This is the behavioral twin of the reference's collector-side graph
(reference: crgc/Shadow.java:10-54, crgc/ShadowGraph.java:9-299).  The TPU
data plane (``arrays.py`` / ``ops/trace.py``) must agree with this oracle
on every liveness verdict; differential tests drive both over the same
entry streams — the same technique the reference author used
(ShadowGraph.java:176-199 ``assertEquals`` dual-graph debugging).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Dict, List, Optional

from ...utils import events
from .messages import StopMsg, WaveMsg
from .state import CrgcContext, Entry

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell
    from .refob import CrgcRefob


def _cell_path(cell) -> str:
    """Stable display name for a cell (real or remote proxy)."""
    return getattr(cell, "path", repr(cell))


class Shadow:
    """Collector-side image of one actor (reference: Shadow.java:10-54)."""

    __slots__ = (
        "self_cell",
        "location",
        "outgoing",
        "supervisor",
        "recv_count",
        "mark",
        "is_root",
        "interned",
        "is_local",
        "is_busy",
        "is_halted",
        "partition",
        "touch_tick",
    )

    def __init__(self) -> None:
        self.self_cell: Optional["ActorCell"] = None
        self.location: Optional[str] = None
        #: cross-node partition id memo (parallel/partition.py) — pure
        #: in the cell's (address, uid), so computed once per shadow
        self.partition: Optional[int] = None
        #: mirror-decay clock (distributed mode): the graph's decay
        #: tick when a fold last mentioned this shadow
        self.touch_tick = 0
        #: net created-minus-deactivated refs toward each target; may be
        #: negative (reference: Shadow.java:14-19)
        self.outgoing: Dict["Shadow", int] = {}
        self.supervisor: Optional["Shadow"] = None
        #: received minus sent; nonzero means undelivered messages exist
        self.recv_count = 0
        self.mark = False
        self.is_root = False
        self.interned = False
        self.is_local = False
        self.is_busy = False
        self.is_halted = False

    def __repr__(self) -> str:  # pragma: no cover
        path = self.self_cell.path if self.self_cell is not None else "?"
        return (
            f"Shadow({path} recv={self.recv_count} root={self.is_root} "
            f"busy={self.is_busy} interned={self.interned} local={self.is_local} "
            f"halted={self.is_halted} out={len(self.outgoing)})"
        )


def _update_outgoing(outgoing: Dict[Shadow, int], target: Shadow, delta: int) -> None:
    """Zero counts are deleted, not stored (reference: ShadowGraph.java:64-73)."""
    count = outgoing.get(target, 0) + delta
    if count == 0:
        outgoing.pop(target, None)
    else:
        outgoing[target] = count


def clear_authoritative_state(shadow: Shadow) -> None:
    """Reset every authoritative slot of one shadow in place (the
    object is kept — other shadows' edges reference it by identity).
    Shared by the distributed absorb path and the sanitizer's oracle
    mirror of it, so the two can never drift on which fields count as
    authoritative."""
    shadow.outgoing.clear()
    shadow.supervisor = None
    shadow.recv_count = 0
    shadow.interned = False
    shadow.is_root = False
    shadow.is_busy = False
    shadow.is_halted = False


def dispatch_kills(cells) -> None:
    """Bulk teardown of a sweep's kill set: one dispatcher submission
    per dispatcher for the whole set, not one per actor (runtime/cell.py
    tell_bulk).  Shared by the single-host trace below and the
    distributed sweep (engines/crgc/distributed.py) — remote cells in
    the set are ProxyCells whose tell routes the StopMsg over the
    fabric."""
    if not cells:
        return
    from ...runtime.cell import tell_bulk

    tell_bulk((cell, StopMsg) for cell in cells)


class ShadowGraph:
    """The detection structure (reference: ShadowGraph.java:9-299)."""

    def __init__(self, context: CrgcContext, local_address: Optional[str] = None):
        self.context = context
        #: address of the node this collector serves; shadows created from
        #: entries are local to it
        self.local_address = local_address
        self.marked = True  # polarity flips every trace (ShadowGraph.java:11)
        self.total_actors_seen = 0
        self.from_set: List[Shadow] = []
        self.shadow_map: Dict["ActorCell", Shadow] = {}
        #: why-live parent capture (telemetry/inspect.py), gated per wake
        #: by the collector exactly like the array backend's flag: when
        #: set, the next trace records ``last_parents`` — a
        #: ``{cell: (parent_cell, kind)}`` map where ``kind`` is
        #: "created" or "supervisor" and pseudoroot seeds are absent
        #: (their explanation is their own flags).
        self.capture_parents = False
        self.last_parents: Optional[Dict[Any, tuple]] = None
        #: accumulated per-edge send matrix ((owner_cell, target_cell)
        #: -> messages sent); None = off, enabled by the liveness
        #: inspector's attach.  Swept cells' rows are purged.
        self.send_matrix: Optional[Dict[tuple, int]] = None

    # ------------------------------------------------------------- #
    # Shadow lookup
    # ------------------------------------------------------------- #

    def get_shadow_for_refob(self, refob: "CrgcRefob") -> Shadow:
        """Cache-aware lookup (reference: ShadowGraph.java:23-33)."""
        shadow = refob.target_shadow
        if shadow is not None and shadow is self.shadow_map.get(refob.target):
            return shadow
        shadow = self.get_shadow(refob.target)
        refob.target_shadow = shadow
        return shadow

    def get_shadow(self, cell: "ActorCell") -> Shadow:
        """(reference: ShadowGraph.java:35-43)"""
        shadow = self.shadow_map.get(cell)
        if shadow is not None:
            return shadow
        return self.make_shadow(cell)

    def make_shadow(self, cell: "ActorCell") -> Shadow:
        """(reference: ShadowGraph.java:45-62)"""
        self.total_actors_seen += 1
        shadow = Shadow()
        shadow.self_cell = cell
        shadow.location = cell.system.address
        shadow.mark = not self.marked  # unmarked under current polarity
        shadow.interned = False
        shadow.is_local = False
        self.shadow_map[cell] = shadow
        self.from_set.append(shadow)
        return shadow

    # ------------------------------------------------------------- #
    # Folding snapshots
    # ------------------------------------------------------------- #

    def merge_entry(self, entry: Entry) -> None:
        """Fold one mutator snapshot (reference: ShadowGraph.java:75-125)."""
        self_shadow = self.get_shadow_for_refob(entry.self_ref)
        self_shadow.interned = True
        self_shadow.is_local = True
        self_shadow.recv_count += entry.recv_count
        self_shadow.is_busy = entry.is_busy
        self_shadow.is_root = entry.is_root

        field_size = self.context.entry_field_size

        # Created refs: owner gains an outgoing edge toward target.
        for i in range(field_size):
            owner = entry.created_owners[i]
            if owner is None:
                break
            target_shadow = self.get_shadow_for_refob(entry.created_targets[i])
            owner_shadow = self.get_shadow_for_refob(owner)
            _update_outgoing(owner_shadow.outgoing, target_shadow, 1)

        # Spawned actors: set the child's supervisor.
        for i in range(field_size):
            child = entry.spawned_actors[i]
            if child is None:
                break
            child_shadow = self.get_shadow_for_refob(child)
            child_shadow.supervisor = self_shadow

        # Updated refobs: sends count against the target's recv balance;
        # deactivations remove an outgoing edge.
        from . import refob as refob_info

        sm = self.send_matrix
        for i in range(field_size):
            target = entry.updated_refs[i]
            if target is None:
                break
            target_shadow = self.get_shadow_for_refob(target)
            info = entry.updated_infos[i]
            send_count = refob_info.count(info)
            if send_count > 0:
                target_shadow.recv_count -= send_count  # may go negative
                if sm is not None:
                    key = (self_shadow.self_cell, target_shadow.self_cell)
                    sm[key] = sm.get(key, 0) + send_count
            if not refob_info.is_active(info):
                _update_outgoing(self_shadow.outgoing, target_shadow, -1)

    def merge_delta(self, delta) -> None:
        """Fold a peer node's compressed batch
        (reference: ShadowGraph.java:127-156)."""
        decoder = delta.decoder()
        for i, delta_shadow in enumerate(delta.shadows):
            shadow = self.get_shadow(decoder[i])
            shadow.interned = shadow.interned or delta_shadow.interned
            shadow.recv_count += delta_shadow.recv_count
            if delta_shadow.interned:
                # isBusy/isRoot are only meaningful if the actor produced
                # an entry in this period (reference: ShadowGraph.java:139-146).
                shadow.is_busy = delta_shadow.is_busy
                shadow.is_root = delta_shadow.is_root
            if delta_shadow.supervisor >= 0:
                shadow.supervisor = self.get_shadow(decoder[delta_shadow.supervisor])
            for target_id, count in delta_shadow.outgoing.items():
                _update_outgoing(
                    shadow.outgoing, self.get_shadow(decoder[target_id]), count
                )

    def merge_undo_log(self, log) -> None:
        """Halt a dead node's actors and revert its unadmitted effects
        (reference: ShadowGraph.java:158-174)."""
        for shadow in self.from_set:
            if shadow.location == log.node_address:
                shadow.is_halted = True
            field = log.admitted.get(shadow.self_cell)
            if field is not None:
                shadow.recv_count += field.message_count
                for target_cell, count in field.created_refs.items():
                    _update_outgoing(
                        shadow.outgoing, self.get_shadow(target_cell), count
                    )

    # ------------------------------------------------------------- #
    # The trace (reference: ShadowGraph.java:201-289)
    # ------------------------------------------------------------- #

    @staticmethod
    def is_pseudo_root(shadow: Shadow) -> bool:
        """(reference: ShadowGraph.java:201-203)"""
        return (
            shadow.is_root
            or shadow.is_busy
            or shadow.recv_count != 0
            or not shadow.interned
        ) and not shadow.is_halted

    def trace(self, should_kill: bool) -> int:
        """Mark-and-sweep over the shadow graph; returns the number of
        garbage actors found.  Unmarked local actors whose supervisor is
        marked get a StopMsg — killing the oldest unmarked ancestor kills
        the subtree via the runtime's stop cascade
        (reference: ShadowGraph.java:205-289)."""
        marked = self.marked
        # Why-live provenance (telemetry/inspect.py): when capture is on
        # for this wake, record which shadow's propagation first marked
        # each non-seed — the pointer-graph twin of the array backend's
        # marking-parent array.
        parents: Optional[Dict[Any, tuple]] = (
            {} if self.capture_parents else None
        )
        with events.recorder.timed(events.TRACING) as ev:
            to_set: List[Shadow] = []
            for shadow in self.from_set:
                if self.is_pseudo_root(shadow):
                    to_set.append(shadow)
                    shadow.mark = marked

            scanptr = 0
            while scanptr < len(to_set):
                owner = to_set[scanptr]
                scanptr += 1
                if owner.is_halted:
                    # Nothing reachable from a halted actor stays alive on
                    # its account (reference: ShadowGraph.java:226-229).
                    continue
                for target, count in owner.outgoing.items():
                    if count > 0 and target.mark != marked:
                        to_set.append(target)
                        target.mark = marked
                        if parents is not None:
                            parents[target.self_cell] = (
                                owner.self_cell, "created",
                            )
                # Mark the supervisor so parents outlive descendants —
                # deliberately incomplete (reference: ShadowGraph.java:242-267).
                supervisor = owner.supervisor
                if supervisor is not None and supervisor.mark != marked:
                    to_set.append(supervisor)
                    supervisor.mark = marked
                    if parents is not None:
                        parents[supervisor.self_cell] = (
                            owner.self_cell, "supervisor",
                        )
            if parents is not None:
                self.last_parents = parents

            num_garbage = 0
            num_live = 0
            # The sweep in its own timed event, for the wake profiler's
            # trace-vs-sweep attribution (telemetry/profile.py).
            with events.recorder.timed(events.SWEEP):
                kills: List[Any] = []
                for shadow in self.from_set:
                    if shadow.mark != marked:
                        num_garbage += 1
                        self.shadow_map.pop(shadow.self_cell, None)
                        if (
                            should_kill
                            and shadow.is_local
                            and not shadow.is_halted
                            and shadow.supervisor is not None
                            and shadow.supervisor.mark == marked
                        ):
                            kills.append(shadow.self_cell)
                    else:
                        num_live += 1
                dispatch_kills(kills)

                self.from_set = to_set
                self.marked = not marked
                sm = self.send_matrix
                if sm and num_garbage:
                    shadow_map = self.shadow_map
                    dead_keys = [
                        key
                        for key in sm
                        if key[0] not in shadow_map or key[1] not in shadow_map
                    ]
                    for key in dead_keys:
                        del sm[key]
            ev.fields["num_garbage_actors"] = num_garbage
            ev.fields["num_live_actors"] = num_live
        return num_garbage

    def start_wave(self) -> int:
        """Poke local roots to flush entries down the tree
        (reference: ShadowGraph.java:291-299)."""
        count = 0
        for shadow in self.from_set:
            if shadow.is_root and shadow.is_local:
                count += 1
                shadow.self_cell.tell(WaveMsg)
        return count

    # ------------------------------------------------------------- #
    # Diagnostics (reference: ShadowGraph.java:176-199, 302-330)
    # ------------------------------------------------------------- #

    def assert_equals(self, other: "ShadowGraph") -> None:
        """Differential-testing check comparing two graphs built from the
        same entry stream (reference: ShadowGraph.java:176-199
        ``assertEquals``).  Raises :class:`GraphMismatchError` — a
        structured error that survives ``python -O`` and carries every
        mismatching entry in its payload — instead of a bare assert."""
        from ...utils.validation import GraphMismatchError

        only_here = set(self.shadow_map) - set(other.shadow_map)
        only_there = set(other.shadow_map) - set(self.shadow_map)
        if only_here or only_there:
            raise GraphMismatchError(
                "graph.population",
                "shadow maps cover different actors",
                only_here=sorted(_cell_path(c) for c in only_here),
                only_there=sorted(_cell_path(c) for c in only_there),
            )
        mismatches: List[dict] = []
        for cell, mine in self.shadow_map.items():
            theirs = other.shadow_map[cell]
            diffs = {}
            for field in ("recv_count", "is_root", "interned", "is_busy"):
                a, b = getattr(mine, field), getattr(theirs, field)
                if a != b:
                    diffs[field] = (a, b)
            mine_sup = mine.supervisor.self_cell if mine.supervisor else None
            their_sup = theirs.supervisor.self_cell if theirs.supervisor else None
            if mine_sup is not their_sup:
                diffs["supervisor"] = (
                    _cell_path(mine_sup) if mine_sup else None,
                    _cell_path(their_sup) if their_sup else None,
                )
            # Compare by cell identity (distinct cells can share a path
            # across nodes); render paths only in the evidence payload.
            mine_out = {s.self_cell: c for s, c in mine.outgoing.items()}
            their_out = {s.self_cell: c for s, c in theirs.outgoing.items()}
            if mine_out != their_out:
                diffs["outgoing"] = (
                    sorted((_cell_path(c), n) for c, n in mine_out.items()),
                    sorted((_cell_path(c), n) for c, n in their_out.items()),
                )
            if diffs:
                mismatches.append({"actor": _cell_path(cell), "fields": diffs})
        if mismatches:
            raise GraphMismatchError(
                "graph.mismatch",
                f"{len(mismatches)} shadow(s) disagree between the graphs",
                mismatches=mismatches,
            )

    def addresses_in_graph(self) -> Dict[str, int]:
        """Uncollected shadows per node address
        (reference: ShadowGraph.java:331-340, structured instead of
        printed)."""
        counts: Dict[str, int] = {}
        for shadow in self.from_set:
            counts[shadow.location] = counts.get(shadow.location, 0) + 1
        return counts

    def investigate_live_set(self) -> Dict[str, object]:
        """Structured dump of why the live set is what it is
        (reference: ShadowGraph.java:342-394): population counters plus
        the cross-locality acquaintances that usually explain a leak
        suspicion (a local actor apparently held remotely, or vice
        versa)."""
        non_interned = roots = busy = nonzero_recv = nonlocal_ = 0
        root_acquaintances: Dict[str, int] = {}
        local_to_remote: List[tuple] = []
        remote_to_local = 0
        for shadow in self.from_set:
            if not shadow.interned:
                non_interned += 1
            if shadow.is_root:
                roots += 1
                root_acquaintances[_cell_path(shadow.self_cell)] = len(
                    shadow.outgoing
                )
            if shadow.is_busy:
                busy += 1
            if shadow.recv_count != 0:
                nonzero_recv += 1
            if not shadow.is_local:
                nonlocal_ += 1
                for out in shadow.outgoing:
                    if out.is_local:
                        remote_to_local += 1
            else:
                for out, count in shadow.outgoing.items():
                    if not out.is_local:
                        local_to_remote.append(
                            (
                                _cell_path(shadow.self_cell),
                                _cell_path(out.self_cell),
                                count,
                            )
                        )
        return {
            "total": len(self.from_set),
            "non_interned": non_interned,
            "roots": roots,
            "busy": busy,
            "nonzero_recv": nonzero_recv,
            "nonlocal": nonlocal_,
            "root_acquaintances": root_acquaintances,
            "local_to_remote": sorted(local_to_remote),
            "remote_to_local_count": remote_to_local,
        }

    def count_reachable_from(self, address: str) -> int:
        """How many actors are reachable from actors at ``address``
        (reference: ShadowGraph.java:302-330)."""
        to_set: List[Shadow] = []
        marked = self.marked
        for shadow in self.from_set:
            if shadow.location == address:
                to_set.append(shadow)
                shadow.mark = marked
        scanptr = 0
        while scanptr < len(to_set):
            owner = to_set[scanptr]
            scanptr += 1
            if owner.is_halted:
                continue
            for target, count in owner.outgoing.items():
                if count > 0 and target.mark != marked:
                    to_set.append(target)
                    target.mark = marked
        for shadow in to_set:
            shadow.mark = not marked
        return len(to_set)
