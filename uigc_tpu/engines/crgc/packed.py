"""Packed mutator->collector entry plane.

The object ``Entry`` snapshot (state.py, reference: crgc/Entry.java:5-37)
is the differential oracle's plane and the multi-node plane (delta graphs
need refob identity).  This module is the single-node hot path the SURVEY
§7 design calls for: a flush writes one packed int64 row into a
per-thread ring buffer, and the collector's drain is array slicing — no
per-entry Python object walk anywhere on the Bookkeeper thread (the
system's single fold bottleneck; the mutator threads, which scale with
the dispatcher pool, pay the flattening instead).

Row layout (width = 4 + 5*E, E = entry-field-size, -1 = empty field):

    col 0          seq       global flush order (busy/root bits are
                             last-writer-wins per actor, so cross-thread
                             total order must be restorable at the fold)
    col 1          self uid  ``ActorCell.uid`` (dense per system)
    col 2          bits      bit0 busy, bit1 root
    col 3          recv      messages received this period
    cols 4..4+2E   E created (owner_uid, target_uid) pairs
    next E         E spawned child uids
    next 2E        E updated (target_uid, packed refob info) pairs

Uids, not slots: slot assignment stays single-writer on the collector
(ArrayShadowGraph.merge_packed maps uids through a dense ``uid -> slot``
array and interns only unseen uids).  The plane's ``uid_strong`` dict
pins every cell named by an in-flight row so the collector can always
resolve it; pins live until the actor's slot is swept
(ArrayShadowGraph._free_slots_batch pops them) — interning alone does
not release a pin, it only makes future lookups bypass it.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional

import numpy as np

ROW_FIXED = 4  # seq, self uid, busy/root bits, recv count


def row_width(entry_field_size: int) -> int:
    return ROW_FIXED + 5 * entry_field_size


class PackedRing:
    """SPSC ring of packed rows: one writer (the mutator thread that owns
    it), one reader (the Bookkeeper).  The writer's fast path takes no
    lock — under the GIL the row store completes before the ``w``
    publish, and the reader never reads at or past ``w``.  The lock
    serializes only the two buffer-wide operations: writer grow and
    reader drain."""

    __slots__ = ("buf", "cap", "r", "w", "lock")

    def __init__(self, width: int, cap: int = 1 << 12):
        assert cap & (cap - 1) == 0
        self.buf = np.empty((cap, width), dtype=np.int64)
        self.cap = cap
        self.r = 0  # read cursor (reader-owned), monotonic
        self.w = 0  # write cursor (writer-owned), monotonic
        self.lock = threading.Lock()

    def begin(self) -> np.ndarray:
        """The next row's buffer view; the reader cannot see it until
        :meth:`commit`.  Stale contents from a previous lap — the caller
        must fill every column."""
        if self.w - self.r >= self.cap:
            # A stale ``r`` read only over-estimates fullness (r is
            # monotonic), so a spurious grow is possible but an
            # overwrite of unread rows is not.
            with self.lock:
                self._grow()
        return self.buf[self.w & (self.cap - 1)]

    def commit(self) -> None:
        self.w += 1

    def _grow(self) -> None:
        # Reader excluded by the lock; relinearize [r, w) from 0.
        cap, r, w = self.cap, self.r, self.w
        new = np.empty((cap * 2, self.buf.shape[1]), dtype=np.int64)
        idx = (np.arange(r, w) & (cap - 1))
        count = w - r
        new[:count] = self.buf[idx]
        self.buf = new
        self.cap = cap * 2
        self.r = 0
        self.w = count

    def drain(self) -> Optional[np.ndarray]:
        """Copy out all committed rows (None if empty)."""
        with self.lock:
            r, w = self.r, self.w
            if r == w:
                return None
            cap = self.cap
            i0 = r & (cap - 1)
            i1 = w & (cap - 1)
            if i0 < i1:
                out = self.buf[i0:i1].copy()
            else:  # wrapped (or exactly full)
                out = np.concatenate([self.buf[i0:], self.buf[:i1]])
            self.r = w
            return out


class PackedPlane:
    """Per-engine bundle: one ring per mutator thread, the global flush
    sequence, and the strong uid->cell pin set."""

    def __init__(self, entry_field_size: int):
        self.entry_field_size = entry_field_size
        self.width = row_width(entry_field_size)
        #: itertools.count.__next__ is a single C call — atomic under
        #: the GIL, so concurrent flushes get distinct ordered stamps.
        self._seq = itertools.count()
        #: cells named by in-flight rows; dict.setdefault / .pop are
        #: individually atomic under the GIL.  Pins persist until the
        #: collector SWEEPS the actor's slot (_free_slots_batch), not
        #: until intern: the graph's cells[] also pins an interned cell,
        #: so the extra pin is redundant but harmless, and releasing it
        #: only at sweep keeps the release single-writer.
        self.uid_strong: Dict[int, object] = {}
        self._rings: Dict[int, PackedRing] = {}
        self._lock = threading.Lock()
        self._tl = threading.local()

    def next_seq(self) -> int:
        return next(self._seq)

    def ring(self) -> PackedRing:
        r = getattr(self._tl, "ring", None)
        if r is None:
            r = PackedRing(self.width)
            with self._lock:
                # Keyed by ring identity, not thread id: thread-id reuse
                # after a worker dies must not alias two rings.  A dead
                # thread's drained-empty ring is a small, bounded leak
                # (the dispatcher pool is fixed-size).
                self._rings[id(r)] = r
            self._tl.ring = r
        return r

    def drain(self) -> Optional[np.ndarray]:
        """All committed rows from every ring, unsorted (merge_packed
        restores flush order from the seq column)."""
        with self._lock:
            rings = list(self._rings.values())
        parts = [p for p in (r.drain() for r in rings) if p is not None]
        if not parts:
            return None
        return parts[0] if len(parts) == 1 else np.concatenate(parts)
