"""CRGC wire messages (reference: crgc/GCMessage.scala:7-21)."""

from __future__ import annotations

from typing import Any, Iterable, Tuple

from ...interfaces import GCMessage, Refob


class AppMsg(GCMessage):
    """An application message wrapped with the refs it carries.  The
    ``window_id`` is stamped by the egress when the message crosses a node
    boundary (reference: GCMessage.scala:7-13, Gateways.scala:83)."""

    __slots__ = ("payload", "_refs", "window_id", "external", "trace_ctx")

    def __init__(self, payload: Any, refs: Iterable[Refob], external: bool = False):
        self.payload = payload
        self._refs: Tuple[Refob, ...] = tuple(refs)
        self.window_id = -1
        #: causal-tracing context, a ``(trace_id, span_id)`` pair or
        #: None (uigc_tpu/telemetry/tracing.py); stamped by the engine's
        #: send path when tracing is on, and carried across node
        #: boundaries in the transport frame header.
        self.trace_ctx = None
        #: True for messages wrapped by the root adapter (sent by
        #: unmanaged code).  External sends carry no sender-side
        #: send-count, so counting them as received would leave the
        #: recipient's receive balance permanently nonzero — the reference
        #: tolerates this because it never collects root shadows at all;
        #: we skip the count so dead roots' shadows can be reclaimed.
        self.external = external

    @property
    def refs(self) -> Tuple[Refob, ...]:
        return self._refs

    def __repr__(self) -> str:
        return f"AppMsg({self.payload!r})"


class _StopMsg(GCMessage):
    """Collector-to-actor kill order (reference: GCMessage.scala:15-17)."""

    __slots__ = ()

    @property
    def refs(self) -> Tuple[Refob, ...]:
        return ()

    def __repr__(self) -> str:
        return "StopMsg"


class _WaveMsg(GCMessage):
    """Wave-style flush trigger, forwarded down the spawn tree
    (reference: GCMessage.scala:19-21, CRGC.scala:137-144)."""

    __slots__ = ()

    @property
    def refs(self) -> Tuple[Refob, ...]:
        return ()

    def __repr__(self) -> str:
        return "WaveMsg"


StopMsg = _StopMsg()
WaveMsg = _WaveMsg()
