"""The CRGC engine: conflict-replicated garbage collection.

Mirrors the reference's default engine (reference: crgc/CRGC.scala:16-242):
every managed actor continuously records local facts into a bounded
``CrgcState``; snapshots flush through a shared queue to the per-node
Bookkeeper; capacity or saturation forces early flushes.  Detection
requires no message ordering and tolerates drops and downed nodes.
"""

from __future__ import annotations

from collections import deque
from typing import TYPE_CHECKING, Any, Callable, Dict, Iterable, Optional

from ...interfaces import GCMessage, Refob, SpawnInfo
from ...runtime.signals import _PostStop
from ...utils import events
from ..engine import Engine, TerminationDecision
from .collector import Bookkeeper
from .messages import AppMsg, StopMsg, WaveMsg, _StopMsg, _WaveMsg
from .refob import CrgcRefob
from .state import CrgcContext, CrgcState, Entry

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell
    from ...runtime.context import ActorContext
    from ...runtime.system import ActorSystem


class CrgcSpawnInfo(SpawnInfo):
    """(reference: CRGC.scala:22-24)"""

    __slots__ = ("creator",)

    def __init__(self, creator: Optional[CrgcRefob]):
        self.creator = creator


class CRGC(Engine):
    """(reference: crgc/CRGC.scala:34-242)"""

    def __init__(self, system: "ActorSystem"):
        super().__init__(system)
        config = system.config
        self.collection_style: str = config.get_string("uigc.crgc.collection-style")
        if self.collection_style not in ("on-idle", "on-block", "wave"):
            raise ValueError(f"bad collection-style {self.collection_style!r}")
        self.crgc_context = CrgcContext(
            delta_graph_size=config.get_int("uigc.crgc.delta-graph-size"),
            entry_field_size=config.get_int("uigc.crgc.entry-field-size"),
        )
        self.num_nodes = config.get_int("uigc.crgc.num-nodes")
        self.wakeup_interval_ms = config.get_int("uigc.crgc.wakeup-interval")
        self.wave_frequency_ms = config.get_int("uigc.crgc.wave-frequency")
        self.egress_finalize_interval_ms = config.get_int(
            "uigc.crgc.egress-finalize-interval"
        )
        self.shadow_graph_impl = config.get_string("uigc.crgc.shadow-graph")
        self.pipelined = config.get_bool("uigc.crgc.pipelined")
        # Distributed (partitioned) collection: each node owns only its
        # shadow-graph slice and cross-node cycles resolve via the
        # dmark wave protocol (engines/crgc/distributed.py).  Only
        # meaningful multi-node; single-node configs fall back to the
        # local collector so one config can serve both shapes.
        self.distributed = (
            config.get_bool("uigc.crgc.distributed") and self.num_nodes > 1
        )
        #: per-address incarnation era as THIS node counts it: bumped
        #: when a downed address rejoins, read by the ingress gateways
        #: so a rejoined incarnation's windows key as (peer, fence) and
        #: never merge with its pre-death stream (gateways.py)
        self._link_fences: Dict[str, int] = {}

        # Mutator->collector channel + entry free list.  CPython deque
        # append/popleft are atomic, giving the lock-free MPSC hand-off the
        # reference gets from ConcurrentLinkedQueue (CRGC.scala:18,52).
        self.queue: deque = deque()
        self.entry_pool: deque = deque()
        self.packed_plane = None

        self.bookkeeper = self.make_bookkeeper()
        self.bookkeeper_cell = system.spawn_system_raw(
            self.bookkeeper, "Bookkeeper", pinned=True
        )

        # Packed entry plane (packed.py): the single-node hot path.
        # Gated off when a fabric is attached — the multi-node fold
        # additionally builds delta graphs from object entries — and for
        # backends without the array fold (the oracle, the native graph).
        graph = self.bookkeeper.shadow_graph
        if (
            config.get_bool("uigc.crgc.packed-entries")
            and system.fabric is None
            and hasattr(graph, "merge_packed")
        ):
            from .packed import PackedPlane

            self.packed_plane = PackedPlane(self.crgc_context.entry_field_size)
            graph.attach_packed_plane(self.packed_plane, system.resolve_cell)

    # Factory hooks so the multi-node engine can substitute richer parts.

    def make_bookkeeper(self) -> Bookkeeper:
        if self.distributed:
            from .distributed import DistributedBookkeeper

            return DistributedBookkeeper(self)
        return Bookkeeper(self)

    def make_shadow_graph(self) -> Any:
        if self.distributed:
            # The partitioned plane: authoritative state only for the
            # owned slice, mirrors for boundary endpoints.  The local
            # fixpoint runs the pointer plane; the device backends keep
            # sharding *within* the node (mesh) and plug in behind the
            # same dmark interface as a follow-on.
            from .distributed import PartitionedShadowGraph

            return PartitionedShadowGraph(self.crgc_context, self.system.address)
        if self.shadow_graph_impl == "oracle":
            from .shadow import ShadowGraph

            return ShadowGraph(self.crgc_context, self.system.address)
        elif self.shadow_graph_impl in ("array", "device", "decremental"):
            from .arrays import ArrayShadowGraph

            return ArrayShadowGraph(
                self.crgc_context,
                self.system.address,
                use_device=(self.shadow_graph_impl in ("device", "decremental")),
                decremental=(self.shadow_graph_impl == "decremental"),
                trace_mode=self.system.config.get_string("uigc.crgc.trace-mode"),
                pull_density=self.system.config.get_float(
                    "uigc.crgc.pull-density"
                ),
            )
        elif self.shadow_graph_impl == "native":
            from ...native import NativeShadowGraph

            return NativeShadowGraph(self.crgc_context, self.system.address)
        elif self.shadow_graph_impl in ("mesh", "mesh-decremental"):
            from .mesh import MeshShadowGraph

            return MeshShadowGraph(
                self.crgc_context,
                self.system.address,
                n_devices=self.system.config.get_int("uigc.crgc.mesh-devices"),
                decremental=(self.shadow_graph_impl == "mesh-decremental"),
                trace_mode=self.system.config.get_string("uigc.crgc.trace-mode"),
                pull_density=self.system.config.get_float(
                    "uigc.crgc.pull-density"
                ),
            )
        raise ValueError(f"bad shadow-graph impl {self.shadow_graph_impl!r}")

    # ----------------------------------------------------------------- #
    # Root support
    # ----------------------------------------------------------------- #

    def root_message(self, payload: Any, refs: Iterable[Refob]) -> GCMessage:
        return AppMsg(payload, refs, external=True)

    def root_spawn_info(self) -> SpawnInfo:
        return CrgcSpawnInfo(creator=None)

    def to_root_refob(self, cell: "ActorCell") -> Refob:
        return CrgcRefob(cell)

    # ----------------------------------------------------------------- #
    # Lifecycle
    # ----------------------------------------------------------------- #

    def init_state(self, cell: "ActorCell", spawn_info: CrgcSpawnInfo) -> CrgcState:
        """(reference: CRGC.scala:69-92)"""
        self_refob = CrgcRefob(cell)
        state = CrgcState(self_refob, self.crgc_context)
        state.record_new_refob(self_refob, self_refob)
        if spawn_info.creator is not None:
            state.record_new_refob(spawn_info.creator, self_refob)
        else:
            state.mark_as_root()

        if self.collection_style == "on-block":
            cell.on_finished_processing = lambda: self.send_entry(state, is_busy=False)
        if (self.collection_style == "wave" and state.is_root) or (
            self.collection_style == "on-idle"
        ):
            self.send_entry(state, is_busy=False)
        return state

    def get_self_ref(self, state: CrgcState, cell: "ActorCell") -> Refob:
        return state.self_ref

    def spawn(
        self,
        factory: Callable[[SpawnInfo], "ActorCell"],
        state: CrgcState,
        ctx: "ActorContext",
    ) -> Refob:
        """(reference: CRGC.scala:100-112)"""
        child = factory(CrgcSpawnInfo(creator=state.self_ref))
        ref = CrgcRefob(child)
        # "onCreate" is only recorded at the child, not the parent.
        if not state.can_record_new_actor():
            self.send_entry(state, is_busy=True)
        state.record_new_actor(ref)
        return ref

    # ----------------------------------------------------------------- #
    # Message path
    # ----------------------------------------------------------------- #

    def send_message(
        self,
        ref: CrgcRefob,
        msg: Any,
        refs: Iterable[Refob],
        state: CrgcState,
        ctx: "ActorContext",
    ) -> None:
        """(reference: CRGC.scala:208-221)"""
        if not ref.can_inc_send_count() or not state.can_record_updated_refob(ref):
            self.send_entry(state, is_busy=True)
        ref.inc_send_count()
        state.record_updated_refob(ref)
        app_msg = AppMsg(msg, refs)
        target = ref.target
        fabric = self.system.fabric
        tel = self.system.telemetry
        if tel is not None and tel.tracer.enabled:
            app_msg.trace_ctx = tel.tracer.on_send(
                target=target.path, uid=target.uid
            )
        tap = self.tap
        if tap is not None:
            tap.on_send(
                target, remote=fabric is not None and target.system is not self.system
            )
        if fabric is not None and target.system is not self.system:
            # Cross-node send: route through the link's egress/ingress
            # interceptors (reference: streams/Egress.scala:19-20).
            fabric.deliver(self.system, target, app_msg)
        else:
            target.tell(app_msg)

    def on_message(
        self, msg: GCMessage, state: CrgcState, ctx: "ActorContext"
    ) -> Optional[Any]:
        """(reference: CRGC.scala:114-127)"""
        if isinstance(msg, AppMsg):
            if not msg.external:
                tap = self.tap
                if tap is not None:
                    tap.on_recv(ctx.cell, crossed=msg.window_id >= 0)
                if not state.can_record_message_received():
                    self.send_entry(state, is_busy=True)
                state.record_message_received()
            return msg.payload
        return None

    def on_idle(
        self, msg: GCMessage, state: CrgcState, ctx: "ActorContext"
    ) -> TerminationDecision:
        """(reference: CRGC.scala:129-149)"""
        if isinstance(msg, _StopMsg):
            return TerminationDecision.SHOULD_STOP
        if isinstance(msg, _WaveMsg):
            self.send_entry(state, is_busy=False)
            for child in ctx.children:
                child.tell(WaveMsg)
            return TerminationDecision.SHOULD_CONTINUE
        if self.collection_style == "on-idle":
            self.send_entry(state, is_busy=False)
        return TerminationDecision.SHOULD_CONTINUE

    # ----------------------------------------------------------------- #
    # Reference management
    # ----------------------------------------------------------------- #

    def create_ref(
        self, target: CrgcRefob, owner: Refob, state: CrgcState, ctx: "ActorContext"
    ) -> Refob:
        """(reference: CRGC.scala:151-162)"""
        ref = CrgcRefob(target.target, target.target_shadow)
        tap = self.tap
        if tap is not None:
            tap.on_create(owner.target, target.target)
        if not state.can_record_new_refob():
            self.send_entry(state, is_busy=True)
        state.record_new_refob(owner, target)
        return ref

    def release(
        self, releasing: Iterable[CrgcRefob], state: CrgcState, ctx: "ActorContext"
    ) -> None:
        """(reference: CRGC.scala:164-177)"""
        tap = self.tap
        for ref in releasing:
            if tap is not None:
                # Before deactivation, so the tap can see a double release.
                tap.on_release(ref, already_released=(ref.info & 1) == 1)
            if not state.can_record_updated_refob(ref):
                self.send_entry(state, is_busy=True)
            ref.deactivate()
            state.record_updated_refob(ref)

    # ----------------------------------------------------------------- #
    # Entry flushing
    # ----------------------------------------------------------------- #

    def _obtain_entry(self) -> Entry:
        """Pop a pooled entry or allocate (reference: CRGC.scala:185-189)."""
        try:
            entry = self.entry_pool.popleft()
            allocated = False
        except IndexError:
            entry = Entry(self.crgc_context)
            allocated = True
        if events.recorder.enabled:
            events.recorder.commit(events.ENTRY_SEND, allocated_memory=allocated)
        return entry

    def send_entry(self, state: CrgcState, is_busy: bool) -> None:
        """(reference: CRGC.scala:179-193)"""
        plane = self.packed_plane
        if plane is not None:
            state.flush_to_ring(is_busy, plane)
            if events.recorder.enabled:
                events.recorder.commit(events.ENTRY_SEND, allocated_memory=False)
            return
        entry = self._obtain_entry()
        state.flush_to_entry(is_busy, entry)
        self.queue.append(entry)

    # ----------------------------------------------------------------- #
    # Remoting interception (reference: CRGC.scala:223-241)
    # ----------------------------------------------------------------- #

    def link_fence(self, address: "str | None") -> int:
        """The incarnation era of ``address`` (0 until it ever rejoins)."""
        return self._link_fences.get(address, 0)

    def bump_link_fence(self, address: str) -> int:
        fence = self._link_fences.get(address, 0) + 1
        self._link_fences[address] = fence
        return fence

    def spawn_egress(self, link: Any) -> Any:
        from .gateways import Egress

        return Egress(link)

    def spawn_ingress(self, link: Any) -> Any:
        from .gateways import Ingress

        return Ingress(link, self)

    # ----------------------------------------------------------------- #
    # Death accounting (divergence from the reference, deliberately)
    # ----------------------------------------------------------------- #
    # The reference's dying actors do not flush their remaining facts,
    # relying on its forked mailbox hook's timing; an actor killed between
    # a send and its flush would leave the recipient's receive balance
    # permanently nonzero (a liveness leak).  We instead account death
    # explicitly: drain-and-count the remaining mailbox, release carried
    # refs, flush a final entry — and account post-mortem arrivals through
    # the dead-letter hook, the single-node analogue of the reference's
    # per-link admitted counts (reference: IngressEntry.java:91-100).

    def pre_signal(self, signal: Any, state: CrgcState, ctx: "ActorContext") -> None:
        if not isinstance(signal, _PostStop):
            return
        leftovers = ctx.cell.drain_mailbox()
        app_msgs = [m for m in leftovers if isinstance(m, AppMsg)]
        if app_msgs:
            # They were never delivered to the user handler; count them in
            # the system's dead-letter metric like any undelivered message.
            self.system.record_dead_letters_dropped(ctx.cell, len(app_msgs))
        for msg in app_msgs:
            if not msg.external:
                if not state.can_record_message_received():
                    self.send_entry(state, is_busy=True)
                state.record_message_received()
            self.release(msg.refs, state, ctx)
        # A stopped actor is no longer a root: without this, a dead root's
        # final entry would leave its shadow a pseudoroot forever, leaking
        # everything it still referenced.
        state.is_root = False
        self.send_entry(state, is_busy=False)

    def on_dead_letter(self, cell: Any, msg: Any) -> None:
        """Account an AppMsg that arrived after the recipient terminated:
        one synthetic receive plus the release of every carried ref, folded
        as an entry on the dead actor's behalf.  ``cell`` may be a
        tombstone ProxyCell when the frame crossed a process boundary and
        the uid no longer resolves — the entry then folds under the same
        stable (address, uid) key the sender's claims fold under, so the
        balances cancel once both sides' facts arrive."""
        if not isinstance(msg, AppMsg):
            return
        refs = list(msg.refs)
        field_size = self.crgc_context.entry_field_size
        first = True
        while first or refs:
            entry = self._obtain_entry()
            entry.self_ref = CrgcRefob(cell)
            entry.recv_count = 1 if first else 0
            batch, refs = refs[:field_size], refs[field_size:]
            for i, ref in enumerate(batch):
                ref.deactivate()
                entry.updated_refs[i] = ref
                entry.updated_infos[i] = ref.info
            self.queue.append(entry)
            first = False

    # ----------------------------------------------------------------- #

    def shutdown(self) -> None:
        self.bookkeeper.stop_timers()

    def on_crash(self) -> None:
        self.bookkeeper.stop_timers()
        # Stop the collector cell: the stop rides the system-message
        # channel, so pending membership events are never processed —
        # an abrupt death, not a graceful leave.
        self.bookkeeper_cell.stop()
