"""Per-link gateways: admitted-message/ref accounting at node boundaries.

Mirrors the reference's Artery stream-stage interceptors (reference:
crgc/Gateways.scala:15-191, crgc/IngressEntry.java:12-158): the egress of
each link stamps outbound AppMsgs with its current window and tallies
them; the ingress tallies what was actually admitted.  When the egress's
window-boundary marker arrives (pushed in-stream, so FIFO with app
messages), the ingress finalizes its own entry and hands it to the local
collector.  These admitted-counts are what make node-crash recovery
possible: the undo log reverts exactly the dead node's *unadmitted*
claims (reference: UndoLog.java:39-93).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Dict, Iterable, Optional

from ...utils import events
from .messages import AppMsg

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell
    from ...runtime.fabric import Link
    from .engine import CRGC


class IngressEntryField:
    """(reference: IngressEntry.java:32-42)"""

    __slots__ = ("message_count", "created_refs")

    def __init__(self) -> None:
        self.message_count = 0
        self.created_refs: Dict["ActorCell", int] = {}

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, IngressEntryField)
            and self.message_count == other.message_count
            and self.created_refs == other.created_refs
        )


class IngressEntry:
    """Per-link tally of admitted messages and refs
    (reference: IngressEntry.java:12-100).

    ``fence`` is the *incarnation era* of the egress peer as counted by
    the tallying node (bumped once per observed death of that address,
    engine ``bump_link_fence``): windows are keyed by (peer, fence), so
    a rejoined incarnation's window ids — which restart from zero —
    can never merge with stragglers of its pre-death stream.

    ``nonce`` is the egress peer's process-incarnation identity (the
    NodeFabric hello nonce) as known to the tallying node when the
    window opened — unlike the fence it is the SAME value at every
    observer, so an undo log can refuse another node's stragglers about
    a previous incarnation outright instead of inferring staleness from
    that node's own (incomparable) era counter.  0 = unknown (an
    in-process fabric, or a frame from a peer that predates the
    field)."""

    __slots__ = (
        "id", "admitted", "egress_address", "ingress_address", "is_final",
        "fence", "nonce",
    )

    def __init__(self) -> None:
        self.id = 0
        self.admitted: Dict["ActorCell", IngressEntryField] = {}
        self.egress_address: Optional[str] = None
        self.ingress_address: Optional[str] = None
        self.is_final = False
        self.fence = 0
        self.nonce = 0

    def on_message(self, recipient: "ActorCell", refs: Iterable[Any]) -> None:
        """(reference: IngressEntry.java:91-100)"""
        field = self.admitted.get(recipient)
        if field is None:
            field = IngressEntryField()
            self.admitted[recipient] = field
        field.message_count += 1
        for refob in refs:
            target = refob.target
            field.created_refs[target] = field.created_refs.get(target, 0) + 1

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, IngressEntry)
            and self.id == other.id
            and self.fence == other.fence
            and self.nonce == other.nonce
            and self.is_final == other.is_final
            and self.egress_address == other.egress_address
            and self.ingress_address == other.ingress_address
            and self.admitted == other.admitted
        )

    # Wire format (reference: IngressEntry.java:103-144 field order).

    def serialize(self, encode_cell) -> bytes:
        import struct

        def pack_str(s):
            b = (s or "").encode()
            return struct.pack(">h", len(b)) + b

        parts = [
            struct.pack(">i?", self.id, self.is_final),
            pack_str(self.ingress_address),
            pack_str(self.egress_address),
            struct.pack(">i", len(self.admitted)),
        ]
        for cell, field in self.admitted.items():
            ref = encode_cell(cell)
            parts.append(struct.pack(">h", len(ref)))
            parts.append(ref)
            parts.append(struct.pack(">ii", field.message_count, len(field.created_refs)))
            for target, count in field.created_refs.items():
                tref = encode_cell(target)
                parts.append(struct.pack(">h", len(tref)))
                parts.append(tref)
                parts.append(struct.pack(">i", count))
        # Fence era + incarnation nonce as trailing fields: decoders
        # that predate them stop at the admitted map (tolerant both
        # directions; a fence-only peer reads the fence and ignores
        # the nonce bytes).
        parts.append(struct.pack(">iQ", self.fence, self.nonce))
        data = b"".join(parts)
        if events.recorder.enabled:
            events.recorder.commit(events.INGRESS_ENTRY_SERIALIZATION, size=len(data))
        return data

    @staticmethod
    def deserialize(buf: bytes, decode_cell) -> "IngressEntry":
        import struct

        offset = 0

        def unpack_str():
            nonlocal offset
            (n,) = struct.unpack_from(">h", buf, offset)
            offset += 2
            s = buf[offset : offset + n].decode()
            offset += n
            return s or None

        entry = IngressEntry()
        entry.id, entry.is_final = struct.unpack_from(">i?", buf, offset)
        offset += 5
        entry.ingress_address = unpack_str()
        entry.egress_address = unpack_str()
        (n_actors,) = struct.unpack_from(">i", buf, offset)
        offset += 4
        for _ in range(n_actors):
            (rlen,) = struct.unpack_from(">h", buf, offset)
            offset += 2
            cell = decode_cell(buf[offset : offset + rlen])
            offset += rlen
            field = IngressEntryField()
            field.message_count, n_refs = struct.unpack_from(">ii", buf, offset)
            offset += 8
            for _ in range(n_refs):
                (tlen,) = struct.unpack_from(">h", buf, offset)
                offset += 2
                target = decode_cell(buf[offset : offset + tlen])
                offset += tlen
                (count,) = struct.unpack_from(">i", buf, offset)
                offset += 4
                field.created_refs[target] = count
            entry.admitted[cell] = field
        if offset + 4 <= len(buf):
            (entry.fence,) = struct.unpack_from(">i", buf, offset)
        if offset + 12 <= len(buf):
            (entry.nonce,) = struct.unpack_from(">Q", buf, offset + 4)
        return entry


class Gateway:
    """(reference: Gateways.scala:25-48)"""

    def __init__(self, egress_address: str, ingress_address: str):
        self.egress_address = egress_address
        self.ingress_address = ingress_address
        self._seqnum = 0
        self.current_entry = self._create_entry()

    def _create_entry(self) -> IngressEntry:
        entry = IngressEntry()
        entry.id = self._seqnum
        entry.egress_address = self.egress_address
        entry.ingress_address = self.ingress_address
        self._seqnum += 1
        return entry

    def finalize_entry(self) -> IngressEntry:
        entry = self.current_entry
        self.current_entry = self._create_entry()
        return entry


class Egress(Gateway):
    """Sender-side interceptor (reference: Gateways.scala:55-115).

    Only stamps the window id and rolls the window on finalize; the
    admitted-count tally lives exclusively at the ingress.  (The
    reference's egress also tallies into its own entry, but that entry's
    content is discarded at the ingress — Gateways.scala:168-171 uses it
    purely as a window-boundary marker — so the duplicate per-message
    bookkeeping is skipped here.)  The fence era a window belongs to is
    stamped by the *ingress* (the tallying side counts the egress
    peer's deaths); the egress needs none."""

    def __init__(self, link: "Link"):
        super().__init__(link.src.address, link.dst.address)

    def on_message(self, recipient: "ActorCell", msg: Any) -> None:
        if isinstance(msg, AppMsg):
            msg.window_id = self.current_entry.id


class Ingress:
    """Receiver-side interceptor; finalized entries go to the local
    collector (reference: Gateways.scala:121-141).

    Admitted tallies are bucketed *by the window id stamped on each
    message*, and a window closes when the egress's boundary marker for
    that id arrives (reference: Gateways.scala:83-94,168-171 finalizes
    the entry matching the in-stream marker).  Next-window messages that
    overtake a marker's processing therefore land in their own entry
    instead of corrupting the closing one — the property that makes the
    async link mode sound."""

    def __init__(self, link: "Link", engine: "CRGC"):
        self.egress_address = link.src.address
        self.ingress_address = link.dst.address
        self.engine = engine
        #: (fence, window_id) -> tally: a rejoined incarnation restarts
        #: its window numbering from zero, and only the fence era keeps
        #: its stream apart from pre-death stragglers of the same ids
        self.entries: Dict[tuple, IngressEntry] = {}
        #: highest window id seen per fence era (the final entry that
        #: joins the crash quorum must outnumber every era window)
        self._max_window: Dict[int, int] = {}

    def _fence(self) -> int:
        """The egress peer's incarnation era, as this node counts it."""
        return self.engine.link_fence(self.egress_address)

    def _nonce(self) -> int:
        """The egress peer's process-incarnation nonce (0 when the
        fabric has none — in-process, or pre-hello)."""
        system = getattr(self.engine, "system", None)
        fabric = getattr(system, "fabric", None)
        if fabric is None:
            return 0
        return fabric.peer_nonce(self.egress_address) or 0

    def _make_entry(self, window_id: int, fence: int) -> IngressEntry:
        entry = IngressEntry()
        entry.id = window_id
        entry.fence = fence
        entry.nonce = self._nonce()
        entry.egress_address = self.egress_address
        entry.ingress_address = self.ingress_address
        return entry

    def on_message(self, recipient: "ActorCell", msg: Any) -> None:
        if isinstance(msg, AppMsg):
            wid = msg.window_id
            fence = self._fence()
            if wid > self._max_window.get(fence, -1):
                self._max_window[fence] = wid
            entry = self.entries.get((fence, wid))
            if entry is None:
                entry = self.entries[(fence, wid)] = self._make_entry(wid, fence)
            entry.on_message(recipient, msg.refs)

    def on_messages(self, recipient: "ActorCell", msgs: list) -> None:
        """Bulk admission tally for a delivered run (runtime/node.py
        ``_admit_app_run``): one gateway call per burst instead of one
        per message — same per-message semantics, the loop just lives
        inside the gateway."""
        entries = self.entries
        fence = self._fence()
        max_w = self._max_window.get(fence, -1)
        for msg in msgs:
            if isinstance(msg, AppMsg):
                wid = msg.window_id
                if wid > max_w:
                    max_w = wid
                entry = entries.get((fence, wid))
                if entry is None:
                    entry = entries[(fence, wid)] = self._make_entry(wid, fence)
                entry.on_message(recipient, msg.refs)
        self._max_window[fence] = max_w

    def _send(self, entry: IngressEntry) -> None:
        from .collector import LocalIngressEntry

        self.engine.bookkeeper_cell.tell(LocalIngressEntry(entry))

    def finalize_window(self, window_id: int, is_final: bool = False) -> None:
        """Close the window the egress marker names (empty entries are
        emitted too — the collector's undo log needs the window sequence
        even when no traffic was admitted).  Markers ride in-stream, so
        the era they close is the link's current one."""
        fence = self._fence()
        if window_id > self._max_window.get(fence, -1):
            self._max_window[fence] = window_id
        entry = self.entries.pop((fence, window_id), None)
        if entry is None:
            entry = self._make_entry(window_id, fence)
        if is_final:
            entry.is_final = True
        self._send(entry)

    def finalize_all(self, is_final: bool = False) -> None:
        """Link death: flush every open window in order (older eras
        first — their markers are never coming), then emit the final
        (possibly empty) entry that joins the crash quorum under the
        dying era (reference: Gateways.scala:129, LocalGC.scala:251-266)."""
        fence = self._fence()
        for key in sorted(self.entries):
            entry = self.entries.pop(key)
            self._send(entry)
        final_entry = self._make_entry(self._max_window.get(fence, -1) + 1, fence)
        final_entry.is_final = is_final
        self._send(final_entry)

    def open_windows(self) -> list:
        """(fence, window_id) pairs still awaiting their boundary marker
        (chaos-bench diagnostics; a healthy link converges to empty
        between finalizations — windows that never close are admitted
        counts the collector will only see at link death)."""
        return sorted(self.entries)

    # Compatibility shim for the lockstep call shape (single window).
    def finalize_and_send(self, is_final: bool = False) -> None:
        self.finalize_all(is_final=is_final)
