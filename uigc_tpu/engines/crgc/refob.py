"""CRGC refobs and the packed send-count/status word.

``refob_info`` mirrors the reference's packed-short encoding exactly
(reference: src/main/java/.../crgc/RefobInfo.java:8-35): the least
significant bit is the deactivated flag, the upper 15 bits are the send
count, and the count saturates to force an early entry flush (reference:
CRGC.scala:215-216).  We keep the 15-bit width — not because Python needs
it, but because the saturation protocol is part of CRGC's wire behavior
and the device data plane packs these words into int16 lanes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Optional

from ...interfaces import Refob

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell

SHORT_MAX = 32767

ACTIVE_REFOB = 0  # (reference: RefobInfo.java:9)


def can_increment(info: int) -> bool:
    """(reference: RefobInfo.java:11-13)"""
    return info <= SHORT_MAX - 2


def inc_send_count(info: int) -> int:
    """(reference: RefobInfo.java:15-17)"""
    return info + 2


def reset_count(info: int) -> int:
    """(reference: RefobInfo.java:19-21)"""
    return 0


def count(info: int) -> int:
    """(reference: RefobInfo.java:23-25)"""
    return info >> 1


def is_active(info: int) -> bool:
    """(reference: RefobInfo.java:27-29)"""
    return (info & 1) == 0


def deactivate(info: int) -> int:
    """Idempotent (reference: RefobInfo.java:31-34)"""
    return info | 1


class CrgcRefob(Refob):
    """A CRGC reference object (reference: crgc/Refob.scala:9-66).

    Carries a mutable packed info word and a one-shot "has been recorded"
    flag used to dedup updated-refob records within an entry period.  The
    ``target_shadow`` cache points into the collector's graph; staleness is
    benign (reference: Refob.scala:12-17).
    """

    __slots__ = ("_target", "target_shadow", "_info", "_has_been_recorded")

    def __init__(self, target: "ActorCell", target_shadow: Any = None):
        self._target = target
        self.target_shadow = target_shadow
        self._info = ACTIVE_REFOB
        self._has_been_recorded = False

    @property
    def target(self) -> "ActorCell":
        return self._target

    @property
    def info(self) -> int:
        return self._info

    @property
    def has_been_recorded(self) -> bool:
        return self._has_been_recorded

    def set_has_been_recorded(self) -> None:
        self._has_been_recorded = True

    def deactivate(self) -> None:
        self._info = deactivate(self._info)

    def inc_send_count(self) -> None:
        self._info = inc_send_count(self._info)

    def can_inc_send_count(self) -> bool:
        return can_increment(self._info)

    def reset(self) -> None:
        """Called when the owning actor flushes this refob into an entry
        (reference: Refob.scala:44-47)."""
        self._info = reset_count(self._info)
        self._has_been_recorded = False

    def __eq__(self, other: Any) -> bool:
        # Refobs compare by target actor (reference: Refob.scala:49-53).
        return isinstance(other, CrgcRefob) and self._target is other._target

    def __hash__(self) -> int:
        return hash(id(self._target))

    def __repr__(self) -> str:
        return f"CrgcRefob({self._target.path})"
