from .engine import CRGC, CrgcSpawnInfo
from .messages import AppMsg, StopMsg, WaveMsg
from .refob import CrgcRefob
from .shadow import Shadow, ShadowGraph
from .state import CrgcContext, CrgcState, Entry

__all__ = [
    "AppMsg",
    "CRGC",
    "CrgcContext",
    "CrgcRefob",
    "CrgcSpawnInfo",
    "CrgcState",
    "Entry",
    "Shadow",
    "ShadowGraph",
    "StopMsg",
    "WaveMsg",
]
