"""Delta graphs: bandwidth-compressed cross-node entry batches.

Mirrors the reference's DeltaGraph/DeltaShadow (reference:
crgc/DeltaGraph.java:22-253, crgc/DeltaShadow.java:11-85): entries are
folded into per-actor delta shadows whose actor refs are encoded as short
ids via a compression table; full graphs are broadcast to every peer
collector, which replays them into its shadow-graph replica.  Binary
serialization uses the same field layout as the reference's hand-rolled
writers (DeltaShadow.serialize: recvCount int, supervisor short, three
flags, outgoing size + (short,int) pairs).
"""

from __future__ import annotations

import struct
from typing import TYPE_CHECKING, Any, Callable, Dict, List, Optional

from ...utils import events
from . import refob as refob_info
from .state import CrgcContext, Entry

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell


class DeltaShadow:
    """(reference: crgc/DeltaShadow.java:11-51)"""

    __slots__ = ("outgoing", "recv_count", "supervisor", "interned", "is_root", "is_busy")

    def __init__(self) -> None:
        self.outgoing: Dict[int, int] = {}
        self.recv_count = 0
        self.supervisor = -1
        self.interned = False
        self.is_root = False
        self.is_busy = False

    def serialize(self) -> bytes:
        """(reference: DeltaShadow.java:57-75 field order)"""
        parts = [
            struct.pack(
                ">ih???i",
                self.recv_count,
                self.supervisor,
                self.interned,
                self.is_root,
                self.is_busy,
                len(self.outgoing),
            )
        ]
        for key, value in self.outgoing.items():
            parts.append(struct.pack(">hi", key, value))
        return b"".join(parts)

    @staticmethod
    def deserialize(buf: bytes, offset: int) -> tuple:
        """Returns (shadow, new_offset) (reference: DeltaShadow.java:77-84)."""
        shadow = DeltaShadow()
        (
            shadow.recv_count,
            shadow.supervisor,
            shadow.interned,
            shadow.is_root,
            shadow.is_busy,
            size,
        ) = struct.unpack_from(">ih???i", buf, offset)
        offset += struct.calcsize(">ih???i")
        for _ in range(size):
            key, value = struct.unpack_from(">hi", buf, offset)
            offset += struct.calcsize(">hi")
            shadow.outgoing[key] = value
        return shadow, offset

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, DeltaShadow)
            and self.outgoing == other.outgoing
            and self.recv_count == other.recv_count
            and self.supervisor == other.supervisor
            and self.interned == other.interned
            and self.is_root == other.is_root
            and self.is_busy == other.is_busy
        )


class DeltaGraph:
    """(reference: crgc/DeltaGraph.java:22-253)"""

    def __init__(self, address: Optional[str], context: CrgcContext):
        self.compression_table: Dict["ActorCell", int] = {}
        self.shadows: List[DeltaShadow] = []
        self.address = address
        self.context = context

    @property
    def size(self) -> int:
        return len(self.shadows)

    def _encode(self, cell: "ActorCell") -> int:
        """(reference: DeltaGraph.java:141-156)"""
        idx = self.compression_table.get(cell)
        if idx is not None:
            return idx
        idx = len(self.shadows)
        self.compression_table[cell] = idx
        self.shadows.append(DeltaShadow())
        return idx

    def merge_entry(self, entry: Entry) -> None:
        """Mirror of the shadow-graph fold, in compressed-id space
        (reference: DeltaGraph.java:73-125)."""
        self_id = self._encode(entry.self_ref.target)
        self_shadow = self.shadows[self_id]
        self_shadow.interned = True
        self_shadow.recv_count += entry.recv_count
        self_shadow.is_busy = entry.is_busy
        self_shadow.is_root = entry.is_root

        field_size = self.context.entry_field_size
        for i in range(field_size):
            owner = entry.created_owners[i]
            if owner is None:
                break
            target_id = self._encode(entry.created_targets[i].target)
            owner_shadow = self.shadows[self._encode(owner.target)]
            self._update_outgoing(owner_shadow.outgoing, target_id, 1)

        for i in range(field_size):
            child = entry.spawned_actors[i]
            if child is None:
                break
            self.shadows[self._encode(child.target)].supervisor = self_id

        for i in range(field_size):
            target = entry.updated_refs[i]
            if target is None:
                break
            info = entry.updated_infos[i]
            target_id = self._encode(target.target)
            send_count = refob_info.count(info)
            if send_count > 0:
                self.shadows[target_id].recv_count -= send_count
            if not refob_info.is_active(info):
                self._update_outgoing(self_shadow.outgoing, target_id, -1)

    @staticmethod
    def _update_outgoing(outgoing: Dict[int, int], target: int, delta: int) -> None:
        count = outgoing.get(target, 0) + delta
        if count == 0:
            outgoing.pop(target, None)
        else:
            outgoing[target] = count

    # ------------------------------------------------------------- #
    # Routed folds (engines/crgc/distributed.py): one entry's effects
    # split per owning partition.  Each method applies exactly the
    # slice of merge_entry that touches ONE actor's authoritative
    # state, so the distributed router can direct every effect to the
    # delta bound for that actor's owner — and nothing else.
    # ------------------------------------------------------------- #

    def touch(self, cell: "ActorCell") -> None:
        """Bare mention: ensure the cell has a (default, non-interned)
        shadow in this delta so the owner's graph learns the actor
        exists — the partitioned twin of merge_entry's on-demand
        ``get_shadow`` for edge endpoints (a never-interned shadow is a
        pseudoroot, which is what keeps the single-host and partitioned
        verdicts identical for actors that only ever appear as created
        targets)."""
        self._encode(cell)

    def fold_self(
        self, cell: "ActorCell", recv_count: int, is_busy: bool, is_root: bool
    ) -> None:
        """The entry's self-actor slice (flags + receive balance)."""
        shadow = self.shadows[self._encode(cell)]
        shadow.interned = True
        shadow.recv_count += recv_count
        shadow.is_busy = is_busy
        shadow.is_root = is_root

    def fold_created(self, owner: "ActorCell", target: "ActorCell") -> None:
        """A created ref: the owner gains an outgoing edge (edges live
        at the SOURCE actor's owner)."""
        target_id = self._encode(target)
        owner_shadow = self.shadows[self._encode(owner)]
        self._update_outgoing(owner_shadow.outgoing, target_id, 1)

    def fold_spawned(self, child: "ActorCell", supervisor: "ActorCell") -> None:
        """A spawn: the child's supervisor pointer (lives at the
        CHILD's owner)."""
        sup_id = self._encode(supervisor)
        self.shadows[self._encode(child)].supervisor = sup_id

    def fold_sends(self, target: "ActorCell", count: int) -> None:
        """Sends count against the target's receive balance (lives at
        the TARGET's owner)."""
        self.shadows[self._encode(target)].recv_count -= count

    def fold_deactivate(self, owner: "ActorCell", target: "ActorCell") -> None:
        """A released ref: the owner loses an outgoing edge (lives at
        the SOURCE actor's owner)."""
        target_id = self._encode(target)
        owner_shadow = self.shadows[self._encode(owner)]
        self._update_outgoing(owner_shadow.outgoing, target_id, -1)

    def compact(self, keep: Callable[["ActorCell", DeltaShadow], bool]) -> "DeltaGraph":
        """A new graph holding only the shadows ``keep`` accepts.  A
        dropped cell that a kept shadow still references (positive or
        negative edge, or supervisor pointer) survives as a BARE entry
        — the ``touch`` semantics — so the kept facts re-fold into an
        identical slice; a dropped cell nothing kept references
        vanishes entirely, unpinning it from the compression table.
        The distributed collector's retained-journal compaction path:
        pruning a fact can only make a re-folded actor look MORE
        alive, never less (leak-safe by construction)."""
        out = DeltaGraph(self.address, self.context)
        decoder = self.decoder()
        for i, sh in enumerate(self.shadows):
            cell = decoder[i]
            if cell is None or not keep(cell, sh):
                continue
            ns = out.shadows[out._encode(cell)]
            ns.interned = sh.interned
            ns.recv_count = sh.recv_count
            ns.is_busy = sh.is_busy
            ns.is_root = sh.is_root
            if sh.supervisor >= 0:
                sup_cell = decoder[sh.supervisor]
                if sup_cell is not None:
                    ns.supervisor = out._encode(sup_cell)
            for tid, cnt in sh.outgoing.items():
                if cnt == 0:
                    continue
                target_cell = decoder[tid]
                if target_cell is not None:
                    ns.outgoing[out._encode(target_cell)] = cnt
        return out

    def decoder(self) -> List["ActorCell"]:
        """(reference: DeltaGraph.java:162-169)"""
        refs: List[Optional["ActorCell"]] = [None] * self.size
        for cell, idx in self.compression_table.items():
            refs[idx] = cell
        return refs  # type: ignore[return-value]

    def is_full(self) -> bool:
        """Headroom guard: one entry can add at most 4*field+1 shadows
        (reference: DeltaGraph.java:174-180)."""
        return (
            self.size + 4 * self.context.entry_field_size + 1
            >= self.context.delta_graph_size
        )

    def non_empty(self) -> bool:
        return self.size > 0

    # ------------------------------------------------------------- #
    # Wire format (reference: DeltaGraph.java:189-232)
    # ------------------------------------------------------------- #

    def serialize(self, encode_cell: Callable[["ActorCell"], bytes]) -> bytes:
        addr = (self.address or "").encode()
        parts = [struct.pack(">h", len(addr)), addr, struct.pack(">h", self.size)]
        for shadow in self.shadows:
            parts.append(shadow.serialize())
        shadow_size = sum(len(p) for p in parts)
        if len(self.compression_table) != self.size:
            from ...utils.validation import WireFormatError

            raise WireFormatError(
                "delta.table_desync",
                "compression table out of sync with shadow list",
                table_size=len(self.compression_table),
                shadow_count=self.size,
                address=self.address,
            )
        for cell, idx in self.compression_table.items():
            ref = encode_cell(cell)
            parts.append(struct.pack(">hh", idx, len(ref)))
            parts.append(ref)
        data = b"".join(parts)
        if events.recorder.enabled:
            # (reference: DeltaGraph.java:190-210 records both sizes)
            events.recorder.commit(
                events.DELTA_GRAPH_SERIALIZATION,
                shadow_size=shadow_size,
                compression_table_size=len(data) - shadow_size,
            )
        return data

    @staticmethod
    def deserialize(
        buf: bytes,
        context: CrgcContext,
        decode_cell: Callable[[bytes], "ActorCell"],
    ) -> "DeltaGraph":
        offset = 0
        (alen,) = struct.unpack_from(">h", buf, offset)
        offset += 2
        address = buf[offset : offset + alen].decode() or None
        offset += alen
        (size,) = struct.unpack_from(">h", buf, offset)
        offset += 2
        graph = DeltaGraph(address, context)
        for _ in range(size):
            shadow, offset = DeltaShadow.deserialize(buf, offset)
            graph.shadows.append(shadow)
        for _ in range(size):
            idx, rlen = struct.unpack_from(">hh", buf, offset)
            offset += 4
            cell = decode_cell(buf[offset : offset + rlen])
            offset += rlen
            graph.compression_table[cell] = idx
        return graph

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, DeltaGraph)
            and self.size == other.size
            and self.compression_table == other.compression_table
            and self.address == other.address
            and self.shadows == other.shadows
        )
