"""Per-actor CRGC state and the entry snapshot it flushes into.

Mirrors the reference's bounded, preallocated mutator-side records
(reference: crgc/State.java:5-124, crgc/Entry.java:5-37): four
fixed-capacity fields (created owner/target pairs, spawned actors, updated
refobs), a saturating receive count, and a move-and-clear flush.  Capacity
checks (``can_record_*``) force an early flush before overflow; the engine
calls them before every record (reference: CRGC.scala:108,121,158,172,215).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional

from ...interfaces import State as StateBase
from ...utils.validation import CapacityError
from . import refob as refob_info
from .refob import SHORT_MAX, CrgcRefob

if TYPE_CHECKING:  # pragma: no cover
    pass


class CrgcContext:
    """Cached CRGC config (reference: crgc/Context.java:8-16)."""

    __slots__ = ("delta_graph_size", "entry_field_size")

    def __init__(self, delta_graph_size: int, entry_field_size: int):
        self.delta_graph_size = delta_graph_size
        self.entry_field_size = entry_field_size


class Entry:
    """A flushed snapshot shipped from a mutator to the collector
    (reference: crgc/Entry.java:5-37).  Pooled and reused."""

    __slots__ = (
        "self_ref",
        "created_owners",
        "created_targets",
        "spawned_actors",
        "updated_refs",
        "updated_infos",
        "recv_count",
        "is_busy",
        "is_root",
    )

    def __init__(self, context: CrgcContext):
        size = context.entry_field_size
        self.self_ref: Optional[CrgcRefob] = None
        self.created_owners: List[Optional[CrgcRefob]] = [None] * size
        self.created_targets: List[Optional[CrgcRefob]] = [None] * size
        self.spawned_actors: List[Optional[CrgcRefob]] = [None] * size
        self.updated_refs: List[Optional[CrgcRefob]] = [None] * size
        self.updated_infos: List[int] = [0] * size
        self.recv_count = 0
        self.is_busy = False
        self.is_root = False

    def clean(self) -> None:
        """Reset for pool reuse (reference: Entry.java:26-36)."""
        self.self_ref = None
        for i in range(len(self.created_owners)):
            self.created_owners[i] = None
            self.created_targets[i] = None
            self.spawned_actors[i] = None
            self.updated_refs[i] = None
            self.updated_infos[i] = 0
        self.recv_count = 0
        self.is_busy = False
        self.is_root = False


class CrgcState(StateBase):
    """Mutable GC state owned by exactly one actor — single-writer by
    design (reference: crgc/State.java:5-43)."""

    __slots__ = (
        "self_ref",
        "context",
        "created_owners",
        "created_targets",
        "spawned_actors",
        "updated_refobs",
        "created_idx",
        "spawned_idx",
        "updated_idx",
        "recv_count",
        "is_root",
        "stop_requested",
    )

    def __init__(self, self_ref: CrgcRefob, context: CrgcContext):
        size = context.entry_field_size
        self.self_ref = self_ref
        self.context = context
        self.created_owners: List[Optional[CrgcRefob]] = [None] * size
        self.created_targets: List[Optional[CrgcRefob]] = [None] * size
        self.spawned_actors: List[Optional[CrgcRefob]] = [None] * size
        self.updated_refobs: List[Optional[CrgcRefob]] = [None] * size
        self.created_idx = 0
        self.spawned_idx = 0
        self.updated_idx = 0
        self.recv_count = 0
        self.is_root = False
        self.stop_requested = False

    def mark_as_root(self) -> None:
        self.is_root = True

    # Capacity checks (reference: State.java:49-88) ------------------- #

    def can_record_new_refob(self) -> bool:
        return self.created_idx < self.context.entry_field_size

    def record_new_refob(self, owner: CrgcRefob, target: CrgcRefob) -> None:
        if not self.can_record_new_refob():
            raise CapacityError(
                "state.capacity",
                "created-refs field written past capacity without a flush",
                field="created",
                index=self.created_idx,
                capacity=self.context.entry_field_size,
                actor=self.self_ref.target.path,
            )
        i = self.created_idx
        self.created_idx = i + 1
        self.created_owners[i] = owner
        self.created_targets[i] = target

    def can_record_new_actor(self) -> bool:
        return self.spawned_idx < self.context.entry_field_size

    def record_new_actor(self, child: CrgcRefob) -> None:
        if not self.can_record_new_actor():
            raise CapacityError(
                "state.capacity",
                "spawned-actors field written past capacity without a flush",
                field="spawned",
                index=self.spawned_idx,
                capacity=self.context.entry_field_size,
                actor=self.self_ref.target.path,
            )
        self.spawned_actors[self.spawned_idx] = child
        self.spawned_idx += 1

    def can_record_updated_refob(self, refob: CrgcRefob) -> bool:
        return refob.has_been_recorded or self.updated_idx < self.context.entry_field_size

    def record_updated_refob(self, refob: CrgcRefob) -> None:
        if not self.can_record_updated_refob(refob):
            raise CapacityError(
                "state.capacity",
                "updated-refobs field written past capacity without a flush",
                field="updated",
                index=self.updated_idx,
                capacity=self.context.entry_field_size,
                actor=self.self_ref.target.path,
                refob=repr(refob),
            )
        if refob.has_been_recorded:
            return
        refob.set_has_been_recorded()
        self.updated_refobs[self.updated_idx] = refob
        self.updated_idx += 1

    def can_record_message_received(self) -> bool:
        return self.recv_count < SHORT_MAX

    def record_message_received(self) -> None:
        if not self.can_record_message_received():
            raise CapacityError(
                "state.capacity",
                "receive count saturated without a flush",
                field="recv_count",
                value=self.recv_count,
                capacity=SHORT_MAX,
                actor=self.self_ref.target.path,
            )
        self.recv_count += 1

    # Flush (reference: State.java:90-124) ----------------------------- #

    def flush_to_entry(self, is_busy: bool, entry: Entry) -> None:
        entry.self_ref = self.self_ref
        entry.is_busy = is_busy
        entry.is_root = self.is_root

        for i in range(self.created_idx):
            entry.created_owners[i] = self.created_owners[i]
            entry.created_targets[i] = self.created_targets[i]
            self.created_owners[i] = None
            self.created_targets[i] = None
        self.created_idx = 0

        for i in range(self.spawned_idx):
            entry.spawned_actors[i] = self.spawned_actors[i]
            self.spawned_actors[i] = None
        self.spawned_idx = 0

        entry.recv_count = self.recv_count
        self.recv_count = 0

        for i in range(self.updated_idx):
            refob = self.updated_refobs[i]
            entry.updated_refs[i] = refob
            entry.updated_infos[i] = refob.info
            refob.reset()
            self.updated_refobs[i] = None
        self.updated_idx = 0

    def flush_to_ring(self, is_busy: bool, plane) -> None:
        """Move-and-clear flush into the packed plane (packed.py row
        layout) instead of an object Entry — same facts, same reset
        semantics as :meth:`flush_to_entry`, but the collector-side fold
        becomes pure array work.  Every cell named by the row is pinned
        in ``plane.uid_strong`` *before* the commit publishes the row,
        so the collector can always resolve the uid."""
        ring = plane.ring()
        us = plane.uid_strong
        v = ring.begin()
        sc = self.self_ref._target
        v[0] = plane.next_seq()
        v[1] = sc.uid
        us.setdefault(sc.uid, sc)
        v[2] = (1 if is_busy else 0) | (2 if self.is_root else 0)
        v[3] = self.recv_count
        self.recv_count = 0
        v[4:] = -1

        base = 4
        for i in range(self.created_idx):
            oc = self.created_owners[i]._target
            tc = self.created_targets[i]._target
            us.setdefault(oc.uid, oc)
            us.setdefault(tc.uid, tc)
            v[base + 2 * i] = oc.uid
            v[base + 2 * i + 1] = tc.uid
            self.created_owners[i] = None
            self.created_targets[i] = None
        self.created_idx = 0

        base += 2 * self.context.entry_field_size
        for i in range(self.spawned_idx):
            cc = self.spawned_actors[i]._target
            us.setdefault(cc.uid, cc)
            v[base + i] = cc.uid
            self.spawned_actors[i] = None
        self.spawned_idx = 0

        base += self.context.entry_field_size
        for i in range(self.updated_idx):
            refob = self.updated_refobs[i]
            tc = refob._target
            us.setdefault(tc.uid, tc)
            v[base + 2 * i] = tc.uid
            v[base + 2 * i + 1] = refob.info
            refob.reset()
            self.updated_refobs[i] = None
        self.updated_idx = 0
        ring.commit()
