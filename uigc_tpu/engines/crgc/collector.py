"""The per-node collector actor ("Bookkeeper").

Mirrors the reference's ``LocalGC`` (reference: crgc/LocalGC.scala:48-282):
a system actor on a pinned thread that periodically drains the mutator
entry queue, folds entries into its shadow graph, and runs the liveness
trace.  Multi-node (num-nodes > 1, attached to a Fabric):

- GC is gated until all ``num-nodes`` members join
  (reference: LocalGC.scala:69-75,206-208);
- drained entries are additionally folded into a DeltaGraph that is
  broadcast to every peer collector when full
  (reference: LocalGC.scala:159-165,191-196);
- per-link ingress entries are merged into undo logs and re-broadcast to
  the other peers (reference: LocalGC.scala:100-122,245-268);
- on member removal, the matching ingress finalizes, and once every
  surviving peer's final entry arrives (the quorum), the undo log is
  folded: the dead node's actors halt and its unadmitted effects revert
  (reference: LocalGC.scala:228-243,251-266).
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import TYPE_CHECKING, Any, Dict, Optional, Set

from ...runtime.behaviors import RawBehavior
from ...runtime.fabric import MemberRemoved, MemberUp
from ...utils import events
from .delta import DeltaGraph
from .gateways import IngressEntry
from .undo import UndoLog

if TYPE_CHECKING:  # pragma: no cover
    from .engine import CRGC


class _Wakeup:
    __slots__ = ()

    def __repr__(self) -> str:
        return "Wakeup"


class _StartWave:
    __slots__ = ()

    def __repr__(self) -> str:
        return "StartWave"


class _FinalizeEgresses:
    __slots__ = ()


WAKEUP = _Wakeup()
START_WAVE = _StartWave()
FINALIZE_EGRESSES = _FinalizeEgresses()


def _phase(wake: Any, name: str):
    """Profiler phase bracket, or a no-op when no wake is active."""
    return wake.phase(name) if wake is not None else nullcontext()


class DeltaMsg:
    """(reference: LocalGC.scala:26-28)"""

    __slots__ = ("seqnum", "graph", "_wire_buf")

    def __init__(self, seqnum: int, graph: DeltaGraph):
        self.seqnum = seqnum
        self.graph = graph
        self._wire_buf: Optional[bytes] = None

    def reencode(self, fabric, dst_system) -> "DeltaMsg":
        """Cross a serialized fabric as the DeltaGraph wire format
        (reference: DeltaGraph.java:189-232).  The encode side is
        destination-independent, so a broadcast serializes once and
        decodes per peer."""
        from ...runtime import wire

        if self._wire_buf is None:
            self._wire_buf = self.graph.serialize(wire.encode_cell)
        graph = DeltaGraph.deserialize(
            self._wire_buf,
            dst_system.engine.crgc_context,
            wire.make_decode_cell(fabric),
        )
        return DeltaMsg(self.seqnum, graph)


class LocalIngressEntry:
    """(reference: LocalGC.scala:16)"""

    __slots__ = ("entry",)

    def __init__(self, entry: IngressEntry):
        self.entry = entry


class RemoteIngressEntry:
    """(reference: LocalGC.scala:35-37)"""

    __slots__ = ("entry", "_wire_buf")

    def __init__(self, entry: IngressEntry):
        self.entry = entry
        self._wire_buf: Optional[bytes] = None

    def reencode(self, fabric, dst_system) -> "RemoteIngressEntry":
        """Cross a serialized fabric as the IngressEntry wire format
        (reference: IngressEntry.java:103-144), encoded once per
        broadcast."""
        from ...runtime import wire

        if self._wire_buf is None:
            self._wire_buf = self.entry.serialize(wire.encode_cell)
        return RemoteIngressEntry(
            IngressEntry.deserialize(self._wire_buf, wire.make_decode_cell(fabric))
        )


class Bookkeeper(RawBehavior):
    """Collector loop (reference: LocalGC.scala:48-282)."""

    def __init__(self, engine: "CRGC"):
        self.engine = engine
        self.cell: Any = None
        self.total_entries = 0
        self.started = False
        self._timer_keys: list = []
        self.shadow_graph = engine.make_shadow_graph()
        #: does the shadow graph hold mutations the last trace has not
        #: seen?  Set by every fold path (entries, packed rows, deltas,
        #: undo folds, wave starts); cleared when a trace runs.  A wake
        #: that folded nothing skips the trace outright — the verdict
        #: is a pure function of graph state, so re-deriving it idle is
        #: pure cost (at mesh scale a no-op wake otherwise pays a full
        #: collective program dispatch, saturating the collector and
        #: convoying every other system on the process-wide collective
        #: lock, which is what stretched crash-recovery quorums from
        #: ms to tens of seconds).
        self._graph_dirty = True
        # Multi-node state (reference: LocalGC.scala:59-67).
        self.remote_gcs: Dict[str, Any] = {}  # address -> peer Bookkeeper cell
        self.undo_logs: Dict[str, UndoLog] = {}
        self.downed_gcs: Set[str] = set()
        #: dead nodes whose undo log has already been folded (folding is
        #: not idempotent, so exactly-once matters)
        self.undone_gcs: Set[str] = set()
        self.delta_graph_id = 0
        self.delta_graph = DeltaGraph(engine.system.address, engine.crgc_context)

    @property
    def multi_node(self) -> bool:
        return self.engine.num_nodes > 1

    # Bound by spawn_system_raw before the first batch runs.
    def bind(self, cell: Any) -> None:
        self.cell = cell
        if not self.multi_node:
            self.start()
        else:
            fabric = self.engine.system.fabric
            if fabric is None:
                raise RuntimeError(
                    "uigc.crgc.num-nodes > 1 requires the system to be "
                    "attached to a Fabric"
                )
            fabric.subscribe(cell)

    def start(self) -> None:
        """Begin periodic collection (reference: LocalGC.scala:211-226)."""
        self.started = True
        timers = self.engine.system.timers
        wakeup_s = self.engine.wakeup_interval_ms / 1000.0
        key = ("crgc-wakeup", id(self))
        self._timer_keys.append(key)
        timers.schedule_fixed_delay(wakeup_s, lambda: self.cell.tell(WAKEUP), key=key)
        if self.engine.collection_style == "wave":
            wave_s = self.engine.wave_frequency_ms / 1000.0
            key = ("crgc-wave", id(self))
            self._timer_keys.append(key)
            timers.schedule_fixed_delay(
                wave_s, lambda: self.cell.tell(START_WAVE), key=key
            )
        if self.multi_node:
            fin_s = self.engine.egress_finalize_interval_ms / 1000.0
            key = ("crgc-egress-finalize", id(self))
            self._timer_keys.append(key)
            timers.schedule_fixed_delay(
                fin_s, lambda: self.cell.tell(FINALIZE_EGRESSES), key=key
            )

    def on_message(self, msg: Any) -> Any:
        if isinstance(msg, _Wakeup):
            if self.started:
                self.collect()
        elif isinstance(msg, _StartWave):
            self.shadow_graph.start_wave()
            self._graph_dirty = True
        elif isinstance(msg, _FinalizeEgresses):
            # (reference: LocalGC.scala:219-224, via ForwardToEgress)
            fabric = self.engine.system.fabric
            for addr in list(self.remote_gcs):
                fabric.finalize_egress(self.engine.system, addr)
        elif isinstance(msg, MemberUp):
            self.add_member(msg.address)
        elif isinstance(msg, MemberRemoved):
            self.remove_member(msg.address)
        elif isinstance(msg, DeltaMsg):
            self.handle_delta(msg.graph)
        elif isinstance(msg, LocalIngressEntry):
            self.handle_local_ingress_entry(msg.entry)
        elif isinstance(msg, RemoteIngressEntry):
            with events.recorder.timed(events.MERGING_INGRESS_ENTRIES):
                self.merge_ingress_entry(msg.entry)
        return None

    # ------------------------------------------------------------- #
    # Membership (reference: LocalGC.scala:198-243)
    # ------------------------------------------------------------- #

    def add_member(self, address: str) -> None:
        if address == self.engine.system.address or not self.multi_node:
            return
        fabric = self.engine.system.fabric
        peer_system = fabric.systems.get(address)
        if peer_system is None:
            return
        self.remote_gcs[address] = peer_system.engine.bookkeeper_cell
        if address in self.downed_gcs:
            # Rejoin of a downed address: a FRESH incarnation after a
            # rolling restart, or the SAME incarnation healing after a
            # partition verdict (``uigc.node.heal-rejoin``).  Either
            # way its re-admitted stream must not fold into the dead
            # era's undo state: reset the log, and clear the one-shot
            # undone latch so a LATER death of the rejoined peer folds
            # again.  If the old log was still awaiting its fold
            # quorum, the skipped fold can only LEAK the dead era's
            # refs (marks stay), never collect a live actor: safe
            # direction — the same argument covers the healed peer's
            # pre-partition contributions, which the death-time fold
            # already reverted (re-sent refs re-register as they
            # arrive).
            self.downed_gcs.discard(address)
            self.undone_gcs.discard(address)
            # Rejoin opens a new incarnation era for the address: the
            # ingress gateways key their windows by (peer, fence) from
            # here on, and the fresh log's fence floor drops pre-death
            # stragglers still in flight (gateways.py fence discipline).
            fence = self.engine.bump_link_fence(address)
            log = UndoLog(
                address, fence=fence, own_address=self.engine.system.address,
                expected_nonce=self._peer_nonce(address),
            )
            prior = self.undo_logs.get(address)
            if prior is not None:
                log.seed_floors(prior)
            self.undo_logs[address] = log
        elif address not in self.undo_logs:
            self.undo_logs[address] = UndoLog(
                address,
                fence=self.engine.link_fence(address),
                own_address=self.engine.system.address,
                expected_nonce=self._peer_nonce(address),
            )
        # Establish both link directions eagerly (the Artery-handshake
        # analogue) so crash-time finalization always has an ingress,
        # even for pairs that never exchanged app messages.
        fabric.link(self.engine.system, peer_system)
        fabric.link(peer_system, self.engine.system)
        if not self.started and len(self.remote_gcs) + 1 == self.engine.num_nodes:
            self.start()

    def _peer_nonce(self, address: str) -> int:
        """The process-incarnation nonce of ``address`` as the fabric
        currently knows it (0 = none): captured into each UndoLog at
        creation so the log is pinned to the incarnation it covers."""
        return self.engine.system.fabric.peer_nonce(address) or 0

    def remove_member(self, address: str) -> None:
        """(reference: LocalGC.scala:228-243)"""
        if address == self.engine.system.address:
            return
        self.downed_gcs.add(address)
        self.remote_gcs.pop(address, None)
        # Finalize the ingress for the dead link (the NewIngressActor hook
        # in the reference, Gateways.scala:129).  In async-link mode the
        # final entry rides the link queue behind any in-flight traffic.
        fabric = self.engine.system.fabric
        fabric.finalize_dead_link(address, self.engine.system)
        # Membership shrank, so quorums that were waiting on the removed
        # node may now be satisfiable — re-check every pending undo log.
        # (The reference only checks on is_final arrival,
        # LocalGC.scala:251-266, which stalls under a second crash.)
        for downed in list(self.downed_gcs):
            self._maybe_fold_undo_log(downed)

    # ------------------------------------------------------------- #
    # Peer traffic (reference: LocalGC.scala:100-142)
    # ------------------------------------------------------------- #

    def handle_delta(self, graph: DeltaGraph) -> None:
        if graph.address in self.remote_gcs:
            with events.recorder.timed(events.MERGING_DELTA_GRAPHS):
                # Only merge from nodes that have not been removed.
                self.shadow_graph.merge_delta(graph)
                self._graph_dirty = True
                self.undo_logs[graph.address].merge_delta_graph(graph)

    def handle_local_ingress_entry(self, entry: IngressEntry) -> None:
        # Tell every remote GC except the one adjacent to this entry
        # (one message object, so serialize mode encodes once).
        fabric = self.engine.system.fabric
        msg = RemoteIngressEntry(entry)
        for addr, gc in self.remote_gcs.items():
            if addr != entry.egress_address:
                fabric.control_send(self.engine.system, gc, msg)
        with events.recorder.timed(events.MERGING_INGRESS_ENTRIES):
            self.merge_ingress_entry(entry)

    def merge_ingress_entry(self, entry: IngressEntry) -> None:
        """(reference: LocalGC.scala:245-268)"""
        addr = entry.egress_address
        log = self.undo_logs.get(addr)
        if log is None:
            log = UndoLog(
                addr,
                fence=self.engine.link_fence(addr),
                own_address=self.engine.system.address,
                expected_nonce=self._peer_nonce(addr),
            )
            self.undo_logs[addr] = log
        if log.stale_fence(entry):
            # A pre-death straggler of a rejoined incarnation: merging
            # it would mix the dead era's windows into the live stream's
            # accounting (the latent (peer, fence) bug).
            events.recorder.commit(
                events.STALE_WINDOW,
                peer=addr,
                ingress=entry.ingress_address,
                window=entry.id,
                fence=entry.fence,
                log_fence=log.fence,
            )
            return
        log.merge_ingress_entry(entry)
        if entry.is_final:
            self._maybe_fold_undo_log(addr)

    def _maybe_fold_undo_log(self, addr: str) -> None:
        """Fold the dead node's undo log exactly once, when our own final
        entry and every surviving peer's are in (the finalization quorum,
        reference: LocalGC.scala:251-266)."""
        if addr in self.undone_gcs:
            return
        log = self.undo_logs.get(addr)
        if log is None:
            return
        my_addr = self.engine.system.address
        if my_addr in log.finalized_by and all(
            peer in log.finalized_by for peer in self.remote_gcs
        ):
            self.undone_gcs.add(addr)
            events.recorder.commit(
                events.UNDO_FOLD,
                address=addr,
                node=my_addr,
                **log.summary(),
            )
            self.shadow_graph.merge_undo_log(log)
            self.shadow_graph.trace(should_kill=True)
            # The fold's own trace consumed the merge, but its kills
            # cascade; leave the next timer wake a fresh derivation.
            self._graph_dirty = True

    # ------------------------------------------------------------- #
    # Collection (reference: LocalGC.scala:144-196)
    # ------------------------------------------------------------- #

    def collect(self) -> int:
        """One collector wake.  Observability wrapping (both optional,
        both attached by ``telemetry.Telemetry``): the whole wake runs
        inside a ``gc_wave`` span whose context becomes the causal
        parent of the terminations it triggers, and the wake profiler
        brackets the pipeline phases (ingest/fold/trace/broadcast here;
        the sweep share is attributed from the ``crgc.sweep`` event the
        backends emit inside their trace)."""
        engine = self.engine
        tel = engine.system.telemetry
        tracer = tel.tracer if tel is not None and tel.tracer.enabled else None
        prof = engine.wake_profiler
        insp = engine.liveness_inspector
        wake = prof.begin_wake() if prof is not None else None
        if hasattr(self.shadow_graph, "sweep_stats"):
            # Device backends collect the per-sweep frontier stats only
            # when a profiler is attached to carry them (arrays.py
            # _stamp_sweep_stats -> WakeProfiler per-wake records).
            self.shadow_graph.sweep_stats = wake is not None
        if hasattr(self.shadow_graph, "capture_parents"):
            # Why-live parent capture follows the same gating discipline:
            # only a liveness inspector that asked for verdict-exact
            # provenance flips the graph onto the parents kernels — a
            # plain wake never pays the capture fixpoint
            # (telemetry/inspect.py).
            self.shadow_graph.capture_parents = (
                insp is not None and insp.parent_capture
            )
        count = n_garbage = 0
        try:
            if tracer is not None:
                with tracer.span("gc_wave", node=engine.system.address) as span:
                    tracer.note_wave(span.ctx)
                    count, n_garbage = self._collect_inner(wake)
                    span.args["entries"] = count
                    span.args["garbage"] = n_garbage
            else:
                count, n_garbage = self._collect_inner(wake)
        finally:
            # A raising wake must still close its profiler accounting,
            # or _active dangles and later sweep/device events are
            # credited to a dead wake.
            if wake is not None:
                wake.end(entries=count, garbage=n_garbage)
        if insp is not None:
            # Flight recorder + leak watchdog ride the collector thread
            # (the one thread that owns the graph, so the read is
            # fold-consistent).  Isolated like any listener: a broken
            # inspector must not stall collection.
            try:
                insp.on_wake(self.shadow_graph, count, n_garbage)
            except Exception:
                events.recorder.commit(
                    events.LISTENER_ERROR, listener="liveness_inspector"
                )
        obs = engine.device_observatory
        if obs is not None:
            # Device observatory: one read-only memory-ledger sample per
            # wake, on the collector thread (fold-consistent, like the
            # inspector's hook) and under the same isolation discipline.
            try:
                obs.on_wake(self.shadow_graph)
            except Exception:
                events.recorder.commit(
                    events.LISTENER_ERROR, listener="device_observatory"
                )
        self._after_wake(n_garbage)
        return count

    def _collect_inner(self, wake: Any) -> tuple:
        """Drain, fold, trace.  Returns ``(num_entries, n_garbage)``."""
        engine = self.engine
        queue = engine.queue
        pool = engine.entry_pool
        count = 0
        multi = self.multi_node
        with events.recorder.timed(events.PROCESSING_ENTRIES) as ev:
            plane = engine.packed_plane
            rows = None
            with _phase(wake, "ingest"):
                if plane is not None:
                    rows = plane.drain()
                batch = []
                while True:
                    try:
                        entry = queue.popleft()
                    except IndexError:
                        break
                    count += 1
                    batch.append(entry)
                    if multi:
                        self.delta_graph.merge_entry(entry)
                        if self.delta_graph.is_full():
                            self.finalize_delta_graph(wake)
            with _phase(wake, "fold"):
                # Packed rows fold first: they happened-before any object
                # entries drained for the same actors (the only object
                # entries in packed mode are dead-letter accounting, which
                # follows the dead actor's packed final flush).
                if rows is not None:
                    count += rows.shape[0]
                    self.shadow_graph.merge_packed(rows)
                if batch:
                    merge_entries = getattr(self.shadow_graph, "merge_entries", None)
                    if merge_entries is not None:
                        # Batched fold: flatten the whole drained queue, then
                        # vectorized scatter-applies (ArrayShadowGraph).
                        merge_entries(batch)
                    else:
                        for entry in batch:
                            self.shadow_graph.merge_entry(entry)
                    for entry in batch:
                        entry.clean()
                        pool.append(entry)
            if multi and self.delta_graph.non_empty():
                self.finalize_delta_graph(wake)
            ev.fields["num_entries"] = count
        self.total_entries += count
        if count:
            self._graph_dirty = True
        graph = self.shadow_graph
        with _phase(wake, "trace"):
            if self.engine.pipelined and getattr(graph, "can_pipeline", False):
                # Pipelined: sweep the previous wake's verdicts (if its
                # device result landed), then dispatch the next wake and
                # return — the device traces while the mutators keep
                # folding (SURVEY §7; sound because CRGC garbage is
                # monotone, see ArrayShadowGraph.launch_trace).  A wake
                # whose result never lands is expired so a transport outage
                # cannot deadlock collection forever.
                n_garbage = 0
                if graph.harvest_ready():
                    n_garbage = graph.harvest_trace(should_kill=True)
                else:
                    graph.expire_stalled_wake(
                        max(30.0, self.engine.wakeup_interval_ms / 1000.0 * 20)
                    )
                graph.launch_trace()
            elif self._graph_dirty:
                # Cleared before the trace: kills the sweep triggers
                # re-dirty through their death-flush entries (and
                # _after_wake re-wakes on progress), so cascades still
                # converge wake by wake.
                self._graph_dirty = False
                n_garbage = graph.trace(should_kill=True)
            else:
                # Nothing folded since the last trace — the verdict
                # cannot have changed; skip the device round-trip.
                n_garbage = 0
        return count, n_garbage

    def _after_wake(self, n_garbage: int) -> None:
        # Cascade acceleration: a wake that killed actors triggers more
        # facts (death flushes, released refs) that usually make MORE
        # actors collectable — a released tree dies level by level.  A
        # fixed cadence pays one full interval per level (the dominant
        # cost of end-to-end collection latency, BENCH_LIVE r4); instead
        # re-wake immediately and let the mailbox round-trip provide the
        # yield that lets the death flushes land first.  Terminates: a
        # re-wake fires only on progress (n_garbage > 0), and garbage is
        # finite.  The reference has no analogue (fixed 50ms delay,
        # LocalGC.scala:213) — at its scale the cascade fits one wake.
        if n_garbage > 0 and self.started:
            self.cell.tell(WAKEUP)

    def diagnostic_dump(self) -> Dict[str, Any]:
        """Structured collector diagnostics (the reference's println
        inspectors, ShadowGraph.java:331-394, as data): per-address
        shadow counts and the live-set breakdown.  Backends without the
        inspectors (e.g. native) report what they have."""
        g = self.shadow_graph
        out: Dict[str, Any] = {
            "total_entries": self.total_entries,
            "members": sorted(self.remote_gcs),
            "downed": sorted(self.downed_gcs),
        }
        if hasattr(g, "addresses_in_graph"):
            out["addresses_in_graph"] = g.addresses_in_graph()
        if hasattr(g, "investigate_live_set"):
            out["live_set"] = g.investigate_live_set()
        return out

    def finalize_delta_graph(self, wake: Any = None) -> None:
        """(reference: LocalGC.scala:191-196).  Profiled as the wake's
        ``broadcast`` phase — the nested-phase accounting keeps it out
        of the enclosing ingest bracket."""
        with _phase(wake, "broadcast"):
            fabric = self.engine.system.fabric
            msg = DeltaMsg(self.delta_graph_id, self.delta_graph)
            for gc in self.remote_gcs.values():
                fabric.control_send(self.engine.system, gc, msg)
            self.delta_graph_id += 1
            self.delta_graph = DeltaGraph(
                self.engine.system.address, self.engine.crgc_context
            )

    def stop_timers(self) -> None:
        for key in self._timer_keys:
            self.engine.system.timers.cancel(key)
        self._timer_keys.clear()

    def on_signal(self, signal: Any) -> Any:
        from ...runtime.signals import _PostStop

        if isinstance(signal, _PostStop):
            self.stop_timers()
        return None
