"""The per-node collector actor ("Bookkeeper").

Mirrors the reference's ``LocalGC`` (reference: crgc/LocalGC.scala:48-282):
a system actor on a pinned thread that periodically drains the mutator
entry queue, folds entries into its shadow graph, and runs the liveness
trace.  Multi-node concerns (delta broadcast, ingress entries, undo logs,
membership gating) are layered on in ``fabric``-aware subclasses/methods.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from ...runtime.behaviors import RawBehavior
from ...utils import events

if TYPE_CHECKING:  # pragma: no cover
    from .engine import CRGC


class _Wakeup:
    __slots__ = ()

    def __repr__(self) -> str:
        return "Wakeup"


class _StartWave:
    __slots__ = ()

    def __repr__(self) -> str:
        return "StartWave"


WAKEUP = _Wakeup()
START_WAVE = _StartWave()


class Bookkeeper(RawBehavior):
    """Single-node collector loop (reference: LocalGC.scala:144-189)."""

    def __init__(self, engine: "CRGC"):
        self.engine = engine
        self.cell: Any = None
        self.total_entries = 0
        self._timer_keys: list = []
        self.shadow_graph = engine.make_shadow_graph()

    # Bound by spawn_system_raw before the first batch runs.
    def bind(self, cell: Any) -> None:
        self.cell = cell
        self.start()

    def start(self) -> None:
        """Begin periodic collection (reference: LocalGC.scala:211-226).
        Single-node systems start immediately; multi-node systems call this
        once membership is complete."""
        timers = self.engine.system.timers
        wakeup_s = self.engine.wakeup_interval_ms / 1000.0
        key = ("crgc-wakeup", id(self))
        self._timer_keys.append(key)
        timers.schedule_fixed_delay(wakeup_s, lambda: self.cell.tell(WAKEUP), key=key)
        if self.engine.collection_style == "wave":
            wave_s = self.engine.wave_frequency_ms / 1000.0
            key = ("crgc-wave", id(self))
            self._timer_keys.append(key)
            timers.schedule_fixed_delay(
                wave_s, lambda: self.cell.tell(START_WAVE), key=key
            )

    def on_message(self, msg: Any) -> Any:
        if isinstance(msg, _Wakeup):
            self.collect()
        elif isinstance(msg, _StartWave):
            self.shadow_graph.start_wave()
        return None

    def collect(self) -> int:
        """One collection pass: drain, fold, trace
        (reference: LocalGC.scala:144-185)."""
        engine = self.engine
        queue = engine.queue
        pool = engine.entry_pool
        count = 0
        with events.recorder.timed(events.PROCESSING_ENTRIES) as ev:
            while True:
                try:
                    entry = queue.popleft()
                except IndexError:
                    break
                count += 1
                self.shadow_graph.merge_entry(entry)
                entry.clean()
                pool.append(entry)
            ev.fields["num_entries"] = count
        self.total_entries += count
        self.shadow_graph.trace(should_kill=True)
        return count

    def stop_timers(self) -> None:
        for key in self._timer_keys:
            self.engine.system.timers.cancel(key)
        self._timer_keys.clear()

    def on_signal(self, signal: Any) -> Any:
        from ...runtime.signals import _PostStop

        if isinstance(signal, _PostStop):
            self.stop_timers()
        return None
