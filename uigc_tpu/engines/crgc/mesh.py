"""Mesh shadow-graph backend: the collector's data plane sharded over a
TPU device mesh.

This is the node-level sharding capability of the reference
(LocalGC.scala:191-196 replicates per-node graphs via DeltaGraph gossip)
re-expressed the TPU way, per SURVEY §7: instead of replicating the graph
per host, the detection state is *partitioned* across the devices of one
slice —

- node feature arrays (flags, recv_count) live device-resident, sharded
  by contiguous slot range over the mesh axis;
- propagation pairs (positive refob edges + supervisor pointers) live
  device-resident as per-destination-shard buckets, so each device's
  scatter lands only in its own node shard;
- each trace wave all_gathers the mark vector over ICI (the collective
  analogue of the DeltaMsg broadcast) and decides convergence with a
  global psum (parallel/sharded_trace.py).

The host keeps its mirror (interning, edge dict, sweep bookkeeping) and
streams *only the per-wake changes* to the device: dirty node rows
(``_node_log``) and pair transitions (``_pair_log``) are scatter-applied
with donated buffers, so steady-state host->device traffic is O(churn),
not O(graph).  Full rebuilds happen only on capacity growth or log
overflow.

Composes with the multi-node path: a cluster of collectors can each run
a mesh graph and still gossip DeltaGraphs/undo logs between hosts — the
mesh shards one node's replica, the fabric replicates across nodes (the
two levels the reference collapses into one).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...ops import trace as trace_ops
from ...ops.slotmap import PackedSlotMap, fold_log, pack_keys, unpack_keys
from ...parallel import sharded_trace
from ...utils import events
from .arrays import ArrayShadowGraph, _readback, audit_donation
from .state import CrgcContext

_SINK_PAD = 64  # scatter batches are padded to multiples of this

#: Serializes sharded-collective dispatch + readback across EVERY
#: MeshShadowGraph in the process.  The virtual CPU mesh (and a real
#: slice) is ONE set of devices; two collector threads concurrently
#: executing all_gather-bearing programs on it can deadlock each other
#: (observed as permanently wedged Bookkeeper threads when several
#: mesh-backend systems coexist in one test process — each program
#: waits for all devices, and the runtime interleaves the two
#: collectives).  Per-wake serialization costs nothing in the
#: steady state — one collector per process is the deployment shape —
#: and makes multi-system processes (the test suite) hang-free.
#: Only the collective-bearing programs (the sharded trace and the
#: decremental wake) need the lock; _sync_device's scatters and folds
#: are per-shard local work with no rendezvous, so they run outside it.
#: Reentrant: the synchronous decremental path dispatches AND reads
#: back under one compute_marks hold.
_MESH_COLLECTIVE_LOCK = threading.RLock()

#: Traced collective programs shared across graphs: every system in a
#: process meshes the same devices, so graphs with identical geometry
#: reuse ONE jit object (and therefore one XLA compilation — first
#: caller compiles under the collective lock, the rest hit the cache
#: instead of serializing ~seconds of duplicate compile work behind it).
#: Bounded: cleared wholesale at the cap (a growing graph re-keys as its
#: padding doubles; without a cap a long-lived process would accumulate
#: one compiled program per geometry ever seen).  A clear only costs a
#: recompile on the next wake of each live geometry.
_SHARED_PROGRAM_CACHE: Dict[tuple, object] = {}
_SHARED_PROGRAM_CACHE_MAX = 32


def _pow2(x: int) -> int:
    return 1 << max(0, int(x - 1).bit_length())


class MeshShadowGraph(ArrayShadowGraph):
    """Shadow graph whose fold/trace state is sharded across a device
    mesh; liveness semantics identical to the host oracle (differential
    tests drive both over the same entry streams)."""

    def __init__(
        self,
        context: CrgcContext,
        local_address: Optional[str] = None,
        n_devices: int = 0,
        initial_capacity: int = 1024,
        decremental: bool = False,
        trace_mode: str = "auto",
        pull_density: float = 0.25,
    ):
        super().__init__(
            context,
            local_address,
            use_device=True,
            initial_capacity=initial_capacity,
            trace_mode=trace_mode,
            pull_density=pull_density,
        )
        import jax

        avail = len(jax.devices())
        if n_devices <= 0:
            n_devices = avail
        # A mesh bigger than the host would silently mis-shard: build_mesh
        # slices jax.devices()[:n] while bucket geometry keeps n, leaving
        # pair_dst offsets relative to the wrong shard origin.
        assert n_devices <= avail, (
            f"uigc.crgc.mesh-devices={n_devices} but only {avail} devices"
        )
        self.n_devices = n_devices
        self.mesh = sharded_trace.build_mesh(n_devices)
        self._fold_fn = sharded_trace.make_sharded_fold(self.mesh, donate=True)
        self._mask_fn = sharded_trace.make_sharded_mask(self.mesh)
        self._node_log = set()  # enable dirty-slot tracking in the base

        from ...ops import pallas_trace as pt

        self.s_rows = pt.S_ROWS
        #: jump/auto trace modes jump marks through a REPLICATED
        #: min-source parent array (every shard runs the same pointer
        #: doubling over replicated tables — no collective needed);
        #: maintained O(churn) from the raw pair log like the
        #: single-device IncrementalPallasLayout.jump_parent
        self._use_jump = trace_mode in (pt.MODE_JUMP, pt.MODE_AUTO)
        self._jump_parent: Optional[np.ndarray] = None
        self._jump_writes: Dict[int, int] = {}
        self._jump_dev = None

        # device state (built lazily on first trace)
        self._dev_ready = False
        self._dev_flags = None
        self._dev_recv = None
        self._n_pad = 0
        self._shard_size = 0
        # --- packed base plane: per-shard Pallas layouts -------------- #
        self._layout_meta: Optional[dict] = None
        self._stacked: Optional[dict] = None  # host truth of the layouts
        self._dev_stacked: Optional[dict] = None
        #: packed (src, dst, kind) key -> (shard << 40 | ri << 8 | col)
        self._base_slot = PackedSlotMap()
        #: queued deletion masks for the device layouts [(shard, ri, col)]
        self._mask_writes: List[Tuple[int, int, int]] = []
        # --- insert buckets: XLA scatter-max tier for new pairs ------- #
        self._bucket_m = 0  # columns per shard (pow2)
        self._pb_src: Optional[np.ndarray] = None  # [D, M] global src ids
        self._pb_dst: Optional[np.ndarray] = None  # [D, M] local dst ids
        self._pb_count: Optional[np.ndarray] = None
        self._pb_free: List[List[int]] = []
        #: packed (src, dst, kind) key -> packed (shard << 32 | column)
        self._pb_slot = PackedSlotMap()
        self.stats = {"rebuilds": 0, "wakes": 0, "anomalies": 0}

        #: per-wake closure+repair detection on the mesh
        #: (parallel/sharded_trace.make_sharded_decremental_wake)
        self.decremental = decremental
        self._wake_state: Optional[list] = None  # mark/seed/halt/iu/active
        self._pending_del_dst: set = set()
        self._pending_fresh_dst: set = set()

        self._jit_cache: Dict[str, object] = {}

    @property
    def can_pipeline(self) -> bool:
        # The mesh pipelined wake overlaps host ingest with the SHARDED
        # decremental wake: launch_trace syncs the shard layouts
        # mesh-natively (the base-class path would have routed through
        # the single-device tracer and desynced them) and dispatches
        # the wake asynchronously; the base class's harvest machinery
        # sweeps the snapshot verdicts through _MeshWakeHandle.
        return self.decremental

    def _start_wake(self) -> tuple:
        """Dispatch the sharded decremental wake asynchronously (the
        base launch_trace keeps the snapshot bookkeeping).  The shard
        layouts sync mesh-natively first; state commits at dispatch
        (like DecrementalTracer.wake_device), so a pending wake
        discarded by a synchronous trace loses nothing."""
        with events.recorder.timed(events.DEVICE_TRACE) as ev:
            ev.fields["trace_mode"] = self.trace_mode
            self._sync_device()
            self.stats["wakes"] += 1
            with _MESH_COLLECTIVE_LOCK:
                out = self._dispatch_decremental_wake(self._layout_meta)
        return _MeshWakeHandle(self), out[0]

    def _shared_program(self, tag: str, meta, factory):
        """Process-wide cache of the traced collective programs, keyed
        by the full geometry (graphs with equal shapes share one jit
        object and one compilation)."""
        key = (
            tag,
            self._n_pad,
            self._shard_size,
            meta["n_blocks"],
            meta["r_rows"],
            self.s_rows,
            self._bucket_m,
            meta["sub"],
            meta["group"],
            self.trace_mode,
            self.pull_density,
            tuple(d.id for d in self.mesh.devices.flat),
            self.mesh.axis_names,
        )
        fn = _SHARED_PROGRAM_CACHE.get(key)
        if fn is None:
            if len(_SHARED_PROGRAM_CACHE) >= _SHARED_PROGRAM_CACHE_MAX:
                _SHARED_PROGRAM_CACHE.clear()
            import time as _time

            t0 = _time.perf_counter()
            built = factory()
            # setdefault: a build race costs one discarded closure, never
            # a duplicate compile (compilation happens at first call).
            fn = _SHARED_PROGRAM_CACHE.setdefault(key, built)
            if events.recorder.enabled:
                # Compile-cache plane (telemetry/device.py): a miss here
                # means a NEW collective program geometry.  One miss per
                # geometry is healthy; a per-wake miss stream for one
                # (tag, geom) is the recompile_storm alert's input.
                events.recorder.commit(
                    events.COMPILE,
                    duration_s=_time.perf_counter() - t0,
                    tag=f"mesh.{tag}",
                    geom=events.compile_geom(key),
                    hit=False,
                )
        elif events.recorder.enabled:
            events.recorder.commit(
                events.COMPILE,
                tag=f"mesh.{tag}",
                geom=events.compile_geom(key),
                hit=True,
            )
        return fn

    # ------------------------------------------------------------- #
    # Device state construction
    # ------------------------------------------------------------- #

    def _sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return (
            NamedSharding(self.mesh, P("gc")),
            NamedSharding(self.mesh, P("gc", None)),
            NamedSharding(self.mesh, P("gc", None, None)),
        )

    def _full_rebuild(self) -> None:
        import jax

        self.stats["rebuilds"] += 1
        D = self.n_devices
        super_sz = self.s_rows * 128
        chunk = D * super_sz
        n_pad = ((self.capacity + chunk - 1) // chunk) * chunk
        self._n_pad = n_pad
        self._shard_size = n_pad // D

        # --- packed base layouts from the host truth -------------- #
        from ...ops.pallas_incremental import IncrementalPallasLayout

        esrc, edst, kinds = IncrementalPallasLayout.pairs_from_graph(
            self.edge_src, self.edge_dst, self.edge_weight, self.supervisor
        )
        stacked, meta, slot_vals = sharded_trace.pack_shard_layouts(
            esrc, edst, n_pad, D, s_rows=self.s_rows
        )
        self._stacked = stacked
        self._layout_meta = meta
        self._base_slot = PackedSlotMap(
            pack_keys(esrc, edst, kinds), slot_vals
        )
        self._mask_writes = []
        if self._use_jump:
            from ...ops import pallas_trace as pt

            self._jump_parent = pt.jump_parents(esrc, edst, n_pad)
            self._jump_writes = {}
            self._jump_dev = None  # re-uploaded (replicated) on first sync

        # --- empty insert buckets --------------------------------- #
        # Sized so the bucket tier absorbs a meaningful fraction of the
        # graph's scale in new pairs before the next rebuild folds them
        # into the packed base (the freeze/consolidate analogue).
        m = _pow2(max(1024, self.capacity // (4 * D)))
        self._bucket_m = m
        self._pb_src = np.full((D, m), self._n_pad, dtype=np.int32)
        self._pb_dst = np.zeros((D, m), dtype=np.int32)
        self._pb_count = np.zeros(D, dtype=np.int64)
        self._pb_free = [[] for _ in range(D)]
        self._pb_slot = PackedSlotMap()

        # --- device arrays ---------------------------------------- #
        nodes_s, pairs_s, pairs3_s = self._sharding()
        flags = np.zeros(n_pad, dtype=np.uint8)
        flags[: self.capacity] = self.flags
        recv = np.zeros(n_pad, dtype=np.int64)
        recv[: self.capacity] = self.recv_count
        self._dev_flags = jax.device_put(flags, nodes_s)
        self._dev_recv = jax.device_put(recv, nodes_s)
        self._dev_stacked = {
            "bmeta1": jax.device_put(stacked["bmeta1"], pairs_s),
            "bmeta2": jax.device_put(stacked["bmeta2"], pairs_s),
            "row_pos": jax.device_put(stacked["row_pos"], pairs3_s),
            "emeta": jax.device_put(stacked["emeta"], pairs3_s),
        }
        self._dev_psrc = jax.device_put(self._pb_src, pairs_s)
        self._dev_pdst = jax.device_put(self._pb_dst, pairs_s)
        # Host mirror of the last recv values synced to the device: the
        # sharded fold applies *deltas* (reference: ShadowGraph.java:75-83
        # folds counts, not absolutes), so per-wake sync needs the diff
        # against what the device already holds.
        self._recv_synced = recv.copy()

        self._pair_log = []
        self._node_log = set()
        self._wake_state = None
        self._pending_del_dst.clear()
        self._pending_fresh_dst.clear()
        self._dev_ready = True

    # ------------------------------------------------------------- #
    # Incremental device sync (O(churn) per wake)
    # ------------------------------------------------------------- #

    def _apply_pair_log(self) -> Optional[list]:
        """Fold pair transitions into the host plane; returns the bucket
        device-scatter batch, or None if the buckets overflowed (full
        rebuild required).  Deletions hitting the packed base mask its
        slot in place (host + queued device mask); deletions hitting the
        bucket free its column; inserts land in the bucket tier.

        Batched like IncrementalPallasLayout.apply_log (the net-effect
        argument and anomaly accounting live in slotmap.fold_log): slot
        lookups are one vectorized binary search per batch."""
        if self._use_jump:
            # Batched jump-parent maintenance — the same
            # pt.fold_jump_log rules as the single-device layout plane
            # (min-fold on insert, invalidate-on-remove, conservative
            # about pairs both inserted and removed in one batch), so
            # the backends cannot diverge on which edges the jump
            # sweep may cross.
            from ...ops import pallas_trace as pt

            pt.fold_jump_log(
                self._jump_parent, self._pair_log, self._n_pad,
                self._jump_writes,
            )
        removes, cond_removes, inserts = fold_log(self._pair_log)
        if self.decremental:
            # Suspect bookkeeping for the decremental wake: removal
            # destinations must re-derive; insert destinations must see
            # their new pair once.  Over-approximation is sound.
            rem = removes + cond_removes
            if rem:
                _, d = unpack_keys(np.fromiter(rem, np.int64, len(rem)))
                self._pending_del_dst.update(d.tolist())
            if inserts:
                _, d = unpack_keys(
                    np.fromiter(inserts, np.int64, len(inserts))
                )
                self._pending_fresh_dst.update(d.tolist())
        writes: Dict[Tuple[int, int], Tuple[int, int]] = {}
        stacked = self._stacked

        def mask_base(packed: int) -> None:
            from ...ops import pallas_trace as pt

            shard = packed >> 40
            ri = (packed >> 8) & 0xFFFFFFFF
            col = packed & 0xFF
            stacked["row_pos"][shard, ri, col] = pt._PAD_ROW
            stacked["emeta"][shard, ri, col] = 0
            self._mask_writes.append((shard, ri, col))

        def free_slot_batch(keys: list, found_is_anomaly: bool) -> None:
            karr = np.fromiter(keys, np.int64, len(keys))
            bucket_vals = self._pb_slot.pop_batch(karr)
            missing = bucket_vals < 0
            base_vals = np.full(karr.size, -1, dtype=np.int64)
            if missing.any():
                base_vals[missing] = self._base_slot.pop_batch(karr[missing])
            for bval, sval in zip(bucket_vals.tolist(), base_vals.tolist()):
                if bval >= 0:
                    if found_is_anomaly:
                        self.stats["anomalies"] += 1
                    shard, colm = bval >> 32, bval & 0xFFFFFFFF
                    self._pb_src[shard, colm] = self._n_pad  # sink
                    self._pb_dst[shard, colm] = 0
                    self._pb_free[shard].append(colm)
                    writes[(shard, colm)] = (self._n_pad, 0)
                elif sval >= 0:
                    if found_is_anomaly:
                        self.stats["anomalies"] += 1
                    mask_base(sval)
                elif not found_is_anomaly:
                    self.stats["anomalies"] += 1

        if removes:
            free_slot_batch(removes, found_is_anomaly=False)
        if cond_removes:
            # insert-first/remove-last: net no-op unless the key was
            # already live (anomalous duplicate insert + real remove).
            free_slot_batch(cond_removes, found_is_anomaly=True)

        if inserts:
            karr = np.fromiter(inserts, np.int64, len(inserts))
            present = (self._pb_slot.get_batch(karr) >= 0) | (
                self._base_slot.get_batch(karr) >= 0
            )
            srcs, dsts = unpack_keys(karr)
            for key, src, dst, dup in zip(
                inserts, srcs.tolist(), dsts.tolist(), present.tolist()
            ):
                if dup:
                    self.stats["anomalies"] += 1
                    continue
                shard = dst // self._shard_size
                free = self._pb_free[shard]
                if free:
                    colm = free.pop()
                else:
                    colm = int(self._pb_count[shard])
                    if colm >= self._bucket_m:
                        return None  # bucket overflow
                    self._pb_count[shard] = colm + 1
                self._pb_slot.add(key, (shard << 32) | colm)
                self._pb_src[shard, colm] = src
                local = dst - shard * self._shard_size
                self._pb_dst[shard, colm] = local
                writes[(shard, colm)] = (src, local)
        self._pair_log = []
        return list(writes.items())

    def _jit(self, name, builder):
        fn = self._jit_cache.get(name)
        if fn is None:
            fn = self._jit_cache[name] = builder()
            if events.recorder.enabled:
                events.recorder.commit(
                    events.COMPILE, tag=f"mesh.scatter.{name}",
                    geom="graph", hit=False,
                )
        elif events.recorder.enabled:
            # Hits commit like every instrumented cache, so the
            # hit/miss shape stays 1-miss-then-hits — without this,
            # N graphs' N innocent builds read as a storm downstream.
            events.recorder.commit(
                events.COMPILE, tag=f"mesh.scatter.{name}",
                geom="graph", hit=True,
            )
        return fn

    def _sync_jump_mirror(self) -> None:
        """Replicated jump-parent device mirror: full upload once per
        rebuild, O(churn) scatter after (same policy as the node
        arrays; replicated because the pointer doubling gathers
        globally on every shard)."""
        if not self._use_jump:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._jump_dev is None:
            repl = NamedSharding(self.mesh, P())
            self._jump_dev = jax.device_put(self._jump_parent, repl)
            self._jump_writes = {}
        elif self._jump_writes:
            w = self._jump_writes
            self._jump_writes = {}
            k = len(w)
            kp = max(_SINK_PAD, _pow2(k))
            idx = np.full(kp, self._n_pad + 1, np.int32)  # OOB -> drop
            vals = np.zeros(kp, np.int32)
            idx[:k] = np.fromiter(w.keys(), np.int64, k)
            vals[:k] = np.fromiter(w.values(), np.int64, k)

            def build_jump():
                @partial(jax.jit, donate_argnums=(0,))
                def apply_jump(jp, idx, vals):
                    return jp.at[idx].set(vals, mode="drop")

                return apply_jump

            donated = self._jump_dev
            self._jump_dev = self._jit("jump", build_jump)(
                donated, idx, vals
            )
            if self.donation_audit:
                audit_donation("mesh.jump", donated)

    def _sync_device(self) -> None:
        if (
            not self._dev_ready
            or self._pair_log is None
            or self._n_pad < self.capacity
        ):
            self._full_rebuild()
            self._sync_jump_mirror()
            return
        pair_writes = self._apply_pair_log() if self._pair_log else []
        if pair_writes is None:
            self._full_rebuild()
            self._sync_jump_mirror()
            return
        import jax
        import jax.numpy as jnp

        if pair_writes:
            k = len(pair_writes)
            kp = max(_SINK_PAD, _pow2(k))
            shs = np.full(kp, self.n_devices, dtype=np.int32)  # OOB -> drop
            cols = np.zeros(kp, dtype=np.int32)
            srcs = np.zeros(kp, dtype=np.int32)
            dsts = np.zeros(kp, dtype=np.int32)
            for i, ((sh, colm), (s, d)) in enumerate(pair_writes):
                shs[i], cols[i], srcs[i], dsts[i] = sh, colm, s, d

            def build_pairs():
                @partial(jax.jit, donate_argnums=(0, 1))
                def apply_pairs(psrc, pdst, shs, cols, srcs, dsts):
                    psrc = psrc.at[shs, cols].set(srcs, mode="drop")
                    pdst = pdst.at[shs, cols].set(dsts, mode="drop")
                    return psrc, pdst

                return apply_pairs

            donated_src, donated_dst = self._dev_psrc, self._dev_pdst
            self._dev_psrc, self._dev_pdst = self._jit("pairs", build_pairs)(
                donated_src, donated_dst, shs, cols, srcs, dsts
            )
            if self.donation_audit:
                audit_donation("mesh.pairs", donated_src, donated_dst)

        if self._mask_writes:
            # base-layout deletions: per-shard in-place masking
            D = self.n_devices
            rows_total = self._stacked["row_pos"].shape[1]
            per_shard: List[List[Tuple[int, int]]] = [[] for _ in range(D)]
            for shard, ri, colm in self._mask_writes:
                per_shard[shard].append((ri, colm))
            self._mask_writes = []
            k = max(_SINK_PAD, _pow2(max(len(p) for p in per_shard)))
            ri = np.full((D, k), rows_total, dtype=np.int32)  # OOB -> drop
            col = np.zeros((D, k), dtype=np.int32)
            for d in range(D):
                for i, (r, c) in enumerate(per_shard[d]):
                    ri[d, i] = r
                    col[d, i] = c
            self._dev_stacked["row_pos"], self._dev_stacked["emeta"] = (
                self._mask_fn(
                    self._dev_stacked["row_pos"],
                    self._dev_stacked["emeta"],
                    ri,
                    col,
                )
            )

        if self._node_log:
            slots_arr = np.fromiter(
                self._node_log, np.int64, len(self._node_log)
            )
            self._node_log = set()
            # Bucket dirty slots by owning shard and run the sharded fold
            # (parallel/sharded_trace.make_sharded_fold): each device
            # scatter-applies only its own shard's rows — recv as deltas
            # against the synced mirror, flags as set/clear masks that
            # reproduce absolute assignment ((old | set) & ~clear = new).
            D = self.n_devices
            ss = self._shard_size
            shard = slots_arr // ss
            order = np.argsort(shard, kind="stable")
            slots_arr = slots_arr[order]
            shard = shard[order]
            counts = np.bincount(shard, minlength=D).astype(np.int64)
            m = max(_SINK_PAD, _pow2(int(counts.max(initial=1))))
            # per-shard local slot buckets, padded with the sink (= ss)
            lslot = np.full((D, m), ss, dtype=np.int32)
            rdelta = np.zeros((D, m), dtype=np.int64)
            fset = np.zeros((D, m), dtype=np.uint8)
            fclear = np.zeros((D, m), dtype=np.uint8)
            starts = np.zeros(D, dtype=np.int64)
            starts[1:] = np.cumsum(counts)[:-1]
            col = np.arange(slots_arr.size, dtype=np.int64) - starts[shard]
            new_flags = self.flags[slots_arr]
            new_recv = self.recv_count[slots_arr]
            lslot[shard, col] = (slots_arr - shard * ss).astype(np.int32)
            rdelta[shard, col] = new_recv - self._recv_synced[slots_arr]
            fset[shard, col] = new_flags
            fclear[shard, col] = ~new_flags
            self._recv_synced[slots_arr] = new_recv
            donated_flags, donated_recv = self._dev_flags, self._dev_recv
            self._dev_flags, self._dev_recv = self._fold_fn(
                donated_flags, donated_recv, lslot, rdelta, fset, fclear
            )
            if self.donation_audit:
                # The sharded fold donates its node shards
                # (sharded_trace.make_sharded_fold(donate=True)); a
                # surviving input means every wake now re-uploads
                # O(graph) node state instead of O(churn) deltas.
                audit_donation("mesh.fold", donated_flags, donated_recv)

        self._sync_jump_mirror()

    # ------------------------------------------------------------- #
    # Trace
    # ------------------------------------------------------------- #

    def _word_array(self, id_set: set):
        """Scatter an id set into the node-word array, sharded like the
        node arrays (word w of shard d covers nodes d*shard + 32w..).
        Empty sets (the quiet steady state) reuse one cached zero array
        instead of allocating + transferring per wake."""
        import jax

        nodes_s, _, _ = self._sharding()
        n_words = self._n_pad // 32
        if not id_set:
            z = getattr(self, "_zero_words", None)
            if z is None or z.shape[0] != n_words:
                z = self._zero_words = jax.device_put(
                    np.zeros(n_words, np.int32), nodes_s
                )
            return z
        words = np.zeros(n_words, dtype=np.uint32)
        ids = np.fromiter(id_set, np.int64, len(id_set))
        np.bitwise_or.at(
            words, ids >> 5, np.uint32(1) << (ids & 31).astype(np.uint32)
        )
        return jax.device_put(words.view(np.int32), nodes_s)

    def compute_marks(self) -> np.ndarray:
        with events.recorder.timed(events.DEVICE_TRACE) as ev:
            ev.fields["trace_mode"] = self.trace_mode
            self._sync_device()
            self.stats["wakes"] += 1
            meta = self._layout_meta
            if self.decremental:
                # One hold spans dispatch AND readback: exactly one
                # collective program is in flight at a time.
                with _MESH_COLLECTIVE_LOCK:
                    return self._compute_marks_decremental(meta)
            traced = self._shared_program(
                "trace",
                meta,
                lambda: sharded_trace.make_sharded_pallas_trace(
                    self.mesh,
                    self._n_pad,
                    self._shard_size,
                    meta["n_blocks"],
                    meta["r_rows"],
                    self.s_rows,
                    self._bucket_m,
                    sub=meta["sub"],
                    group=meta["group"],
                    mode=self.trace_mode,
                    pull_density=self.pull_density,
                ),
            )
            jump = (self._jump_dev,) if self._use_jump else ()
            with _MESH_COLLECTIVE_LOCK:
                mark = traced(
                    self._dev_flags,
                    self._dev_recv,
                    self._dev_stacked["bmeta1"],
                    self._dev_stacked["bmeta2"],
                    self._dev_stacked["row_pos"],
                    self._dev_stacked["emeta"],
                    self._dev_psrc,
                    self._dev_pdst,
                    *jump,
                )
                return _readback(mark, "marks.mesh")[: self.capacity]

    def _dispatch_decremental_wake(self, meta) -> tuple:
        """Dispatch one closure+repair wake on the mesh (regional
        re-derivation per shard, one word all_gather per sweep; a
        zeroed previous state — cold start, post-rebuild — is the full
        derivation).  State and suspects COMMIT at dispatch; an
        async-poisoned result surfaces at the first readback, where the
        caller invalidates so the next wake re-derives from zero state
        instead of feeding poisoned arrays forever."""
        import jax

        wake = self._shared_program(
            "dec",
            meta,
            lambda: sharded_trace.make_sharded_decremental_wake(
                self.mesh,
                self._n_pad,
                self._shard_size,
                meta["n_blocks"],
                meta["r_rows"],
                self.s_rows,
                self._bucket_m,
                sub=meta["sub"],
                group=meta["group"],
                mode=self.trace_mode,
                pull_density=self.pull_density,
            ),
        )
        if self._wake_state is None:
            nodes_s, _, _ = self._sharding()
            z = jax.device_put(
                np.zeros(self._n_pad // 32, np.int32), nodes_s
            )
            self._wake_state = [z] * 5
        del_w = self._word_array(self._pending_del_dst)
        fresh_w = self._word_array(self._pending_fresh_dst)
        jump = (self._jump_dev,) if self._use_jump else ()
        out = wake(
            self._dev_flags,
            self._dev_recv,
            del_w,
            fresh_w,
            *self._wake_state,
            self._dev_stacked["bmeta1"],
            self._dev_stacked["bmeta2"],
            self._dev_stacked["row_pos"],
            self._dev_stacked["emeta"],
            self._dev_psrc,
            self._dev_pdst,
            *jump,
        )
        self._wake_state = list(out[1:])
        self._pending_del_dst.clear()
        self._pending_fresh_dst.clear()
        return out

    def _compute_marks_decremental(self, meta) -> np.ndarray:
        # same readback + poisoned-result recovery as the pipelined path
        return _MeshWakeHandle(self).unpack_marks(
            self._dispatch_decremental_wake(meta)[0]
        )

    def invalidate_wake_state(self) -> None:
        """Drop the previous-fixpoint state (failed/poisoned wake): the
        next wake is a full derivation and pending suspects are moot."""
        self._wake_state = None
        self._pending_del_dst.clear()
        self._pending_fresh_dst.clear()


class _MeshWakeHandle:
    """Adapter giving the base class's pipelined harvest machinery
    (ArrayShadowGraph.harvest_trace / expire_stalled_wake) the two
    operations it needs from an in-flight mesh wake.  The wake's state
    was already committed at dispatch, so unpacking is a pure readback;
    a poisoned result auto-invalidates, same contract as
    DecrementalTracer.unpack_marks."""

    __slots__ = ("graph", "n")

    #: this handle's unpack_marks routes its device->host crossing
    #: through _readback itself; the base harvest must not re-account it
    accounts_readback = True

    def __init__(self, graph: "MeshShadowGraph"):
        self.graph = graph
        #: capacity at launch: the harvest sweeps against the LAUNCH
        #: snapshot, so the mark vector must match the snapshot's
        #: length even if capacity grew in between (the base harvest
        #: pads the grown tail — no verdict exists for it)
        self.n = graph.capacity

    def unpack_marks(self, mark_dev) -> np.ndarray:
        try:
            # Readback waits for the in-flight collective; take the
            # process-wide mesh lock so it cannot interleave with
            # another graph's dispatch (see _MESH_COLLECTIVE_LOCK).
            with _MESH_COLLECTIVE_LOCK:
                return _readback(mark_dev, "marks.mesh_harvest")[: self.n]
        except Exception:
            self.graph.invalidate_wake_state()
            raise

    def invalidate(self) -> None:
        self.graph.invalidate_wake_state()
