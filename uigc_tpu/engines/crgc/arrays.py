"""The array-oriented shadow graph: dense slots + COO edges.

The TPU-first redesign of the collector's detection structure.  Where the
reference holds a ``HashMap<ActorRef, Shadow>`` of pointer-linked shadows
(reference: ShadowGraph.java:9-21, Shadow.java:10-54), this implementation
interns actors into dense integer slots and keeps all node state in flat
numpy arrays — exactly the layout the trace kernels (ops/trace.py) consume
and the layout that ships to the device.  The fold (merge_entry) is a
host-side scatter; the trace runs either on host (numpy) or on the TPU
(JAX), selected by ``use_device``.

Liveness semantics are identical to the oracle ShadowGraph; differential
tests (tests/test_trace_parity.py) drive both over the same entry streams
and compare verdicts — the reference author's own dual-graph technique
(reference: ShadowGraph.java:176-199).

One deliberate divergence: when a garbage node's slot is freed, all edges
incident to it are deleted.  The oracle (like the reference) leaves inert
negative-count edges keyed by dead Shadow objects in live actors' outgoing
maps (reference: ShadowGraph.java:64-73 never purges); those edges can
never propagate marks again (a positive edge to garbage is impossible), so
dropping them preserves liveness verdicts while keeping slots recyclable.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional, Set

import numpy as np

from ...ops import pallas_incremental as pallas_incremental_kinds
from ...ops import trace as trace_ops
from ...ops.i64map import I64Map, IntStack
from ...utils import events
from ...utils.validation import require
from . import refob as refob_info
from .messages import StopMsg, WaveMsg
from .state import CrgcContext, Entry

if TYPE_CHECKING:  # pragma: no cover
    from ...runtime.cell import ActorCell
    from .refob import CrgcRefob

_F = trace_ops
_PAIR_EDGE = pallas_incremental_kinds.EDGE
_PAIR_SUP = pallas_incremental_kinds.SUP


def _readback(value, site: str) -> np.ndarray:
    """The sanctioned device->host crossing on collector paths:
    materialize ``value`` on host and account the transfer as a
    ``tpu.host_transfer`` event (site + bytes; the device observatory
    attributes it to the active wake phase).  uigc-lint UL011 flags
    unannotated ``np.asarray``/``.item()``/``device_get`` calls under
    ``engines/`` and ``ops/`` — route readbacks through here."""
    out = np.asarray(value)  # readback: the sanctioned crossing itself
    if events.recorder.enabled:
        events.recorder.commit(
            events.HOST_TRANSFER, site=site, bytes=int(out.nbytes)
        )
    return out


def audit_donation(site: str, *bufs) -> None:
    """After a donating jitted call returns: every donated operand must
    have been consumed (``is_deleted()`` true).  A survivor means XLA
    silently copied instead of aliasing — the wake pays double HBM
    traffic at that site every time — committed as ``tpu.donation_copy``
    (the device observatory's donation-audit plane).  Host arrays
    (numpy) handed to a donating call are the same bug by construction:
    nothing can be donated, a device copy is forced."""
    if not events.recorder.enabled:
        return
    for buf in bufs:
        if buf is None:
            continue
        deleted = getattr(buf, "is_deleted", None)
        try:
            consumed = bool(deleted()) if deleted is not None else False
        except Exception:
            continue  # indeterminate (backend quirk): don't cry wolf
        if not consumed:
            events.recorder.commit(
                events.DONATION_COPY,
                site=site,
                bytes=int(getattr(buf, "nbytes", 0) or 0),
            )


class ArrayShadowGraph:
    """Dense-slot shadow graph with kernel-backed tracing."""

    def __init__(
        self,
        context: CrgcContext,
        local_address: Optional[str] = None,
        use_device: bool = False,
        decremental: bool = False,
        initial_capacity: int = 1024,
        trace_mode: str = "auto",
        pull_density: float = 0.25,
    ):
        from ...ops import pallas_trace as _pt

        self.context = context
        self.local_address = local_address
        self.use_device = use_device
        #: device-trace propagation strategy (uigc.crgc.trace-mode;
        #: pallas_trace MODE_* docs) + the auto mode's pull threshold
        require(
            trace_mode in _pt.TRACE_MODES, "config.trace_mode",
            "bad uigc.crgc.trace-mode", mode=trace_mode,
            valid=_pt.TRACE_MODES,
        )
        self.trace_mode = trace_mode
        self.pull_density = pull_density
        #: collect the per-sweep frontier decomposition (with_stats
        #: fixpoint + device->host stat readback) this wake.  Set by the
        #: collector when a wake profiler is attached — the only
        #: consumer that carries the fields into per-wake records — so
        #: metrics-only or sanitizer-only telemetry setups never pay
        #: the stats variant on the wake path.
        self.sweep_stats = False
        #: capture the marking-parent array on the next trace (the
        #: why-live provenance forest, telemetry/inspect.py).  Gated
        #: exactly like ``sweep_stats``: the collector sets it per wake
        #: only when a liveness inspector asked for verdict-exact
        #: capture, so plain wakes run the parent-free kernels and pay
        #: nothing.
        self.capture_parents = False
        #: (mark, parent) of the last captured trace: ``last_parents[i]``
        #: is the slot whose propagation first marked slot ``i`` at that
        #: verdict, -1 for pseudoroot seeds/unmarked.  Slots on a parent
        #: chain are all marked, so the sweep that follows the capture
        #: never frees a slot the chain names.
        self.last_parents: Optional[np.ndarray] = None
        self.last_parents_mark: Optional[np.ndarray] = None
        #: probe donated buffers after donating jitted calls and commit
        #: ``tpu.donation_copy`` when one survived (see audit_donation).
        #: Enabled by the device observatory's attach
        #: (uigc_tpu/telemetry/Telemetry); off, the donating sites pay
        #: one bool check.
        self.donation_audit = False
        #: accumulated per-edge send matrix: packed (src << 32 | dst)
        #: slot key -> messages sent since enablement.  None (default)
        #: = off; the liveness inspector's attach enables it by
        #: assigning a dict.  Fed by every fold plane; rows naming a
        #: swept slot are purged with the slot.
        self.send_matrix: Optional[Dict[int, int]] = None
        #: per-wake closure+repair detection relative to the previous
        #: fixpoint (ops/pallas_decremental.py) instead of a full
        #: re-trace from seeds; works in interpret mode too, so it is
        #: not gated on the platform check.
        assert not decremental or use_device, (
            "decremental detection runs on the device trace path"
        )
        self.decremental = decremental
        self._dec = None
        self.total_actors_seen = 0

        cap = max(16, initial_capacity)
        self.capacity = cap
        self.flags = np.zeros(cap, dtype=np.uint8)
        self.recv_count = np.zeros(cap, dtype=np.int64)
        self.supervisor = np.full(cap, -1, dtype=np.int32)
        self.cells: List[Optional["ActorCell"]] = [None] * cap
        self.locations: List[Optional[str]] = [None] * cap

        self.slot_of: Dict["ActorCell", int] = {}
        self.free_slots = IntStack.from_range(0, cap)

        #: packed-plane maps (merge_packed): dense uid -> slot, and the
        #: reverse per-slot uid so freeing a slot invalidates its uid
        #: mapping.  -1 = unmapped.
        self._uid_to_slot = np.full(1024, -1, dtype=np.int64)
        self._slot_uid = np.full(cap, -1, dtype=np.int64)
        #: per-slot flush stamps guarding last-writer-wins writes
        #: against out-of-order ring drains (see _apply_batch)
        self._br_seq = np.full(cap, -1, dtype=np.int64)
        self._sup_seq = np.full(cap, -1, dtype=np.int64)
        self._plane = None
        self._resolve_cell = None

        ecap = max(16, initial_capacity * 2)
        self.edge_capacity = ecap
        self.edge_src = np.zeros(ecap, dtype=np.int32)
        self.edge_dst = np.zeros(ecap, dtype=np.int32)
        self.edge_weight = np.zeros(ecap, dtype=np.int64)
        #: packed (owner << 32 | target) int64 key -> edge id.  An edge is
        #: allocated iff its weight is nonzero, which is what lets the
        #: sweep find every edge incident to a garbage set with one
        #: vectorized scan instead of per-slot incident sets.  A
        #: vectorized hash table, not a dict: the fold's per-batch key
        #: traffic is the collector's hottest map (ops/i64map.py).
        self.edge_of = I64Map()
        self.free_edges = IntStack.from_range(0, ecap)

        #: changelog of pair transitions since the Pallas layout last
        #: consumed it: (insert?, src, dst, kind).  ``None`` means either
        #: "no consumer yet" or "too much churn / geometry change" — the
        #: consumer does a full rebuild (which re-enables the log).  Off
        #: by default so backends that never consume it (host array, the
        #: XLA trace off-TPU) pay one None check per mutation instead of
        #: accumulating up to ``_log_cap`` dead tuples.
        self._pair_log: Optional[List[tuple]] = None
        self._log_cap = 1 << 20
        self._inc = None  # lazily-built IncrementalPallasLayout
        #: slots whose flags/recv changed since last consumed; enabled
        #: (non-None) by backends that mirror node features elsewhere
        #: (the mesh backend's sharded device arrays)
        self._node_log: Optional[Set[int]] = None

    # ------------------------------------------------------------- #
    # Capacity management (static-shape friendly: powers of two)
    # ------------------------------------------------------------- #

    def _grow_nodes(self) -> None:
        old = self.capacity
        new = old * 2
        self.flags = np.concatenate([self.flags, np.zeros(old, dtype=np.uint8)])
        self.recv_count = np.concatenate(
            [self.recv_count, np.zeros(old, dtype=np.int64)]
        )
        self.supervisor = np.concatenate(
            [self.supervisor, np.full(old, -1, dtype=np.int32)]
        )
        self.cells.extend([None] * old)
        self.locations.extend([None] * old)
        self.free_slots.push_range(old, new)
        self._slot_uid = np.concatenate(
            [self._slot_uid, np.full(old, -1, dtype=np.int64)]
        )
        self._br_seq = np.concatenate(
            [self._br_seq, np.full(old, -1, dtype=np.int64)]
        )
        self._sup_seq = np.concatenate(
            [self._sup_seq, np.full(old, -1, dtype=np.int64)]
        )
        self.capacity = new
        # Node capacity sets the bit-table/supertile geometry: the whole
        # Pallas layout must be rebuilt.
        self._pair_log = None
        self._inc = None
        self._dec = None

    def _grow_edges(self, min_free: int = 1) -> None:
        """Grow in one jump to whatever power-of-two capacity yields
        ``min_free`` free ids — a large batch must not pay one
        array-copy per doubling."""
        old = self.edge_capacity
        new = old * 2
        while new - old + len(self.free_edges) < min_free:
            new *= 2
        self.edge_src = np.concatenate(
            [self.edge_src, np.zeros(new - old, dtype=np.int32)]
        )
        self.edge_dst = np.concatenate(
            [self.edge_dst, np.zeros(new - old, dtype=np.int32)]
        )
        self.edge_weight = np.concatenate(
            [self.edge_weight, np.zeros(new - old, dtype=np.int64)]
        )
        self.free_edges.push_range(old, new)
        self.edge_capacity = new

    # ------------------------------------------------------------- #
    # Interning
    # ------------------------------------------------------------- #

    def slot_for(self, cell: "ActorCell") -> int:
        """Get-or-create the dense slot for an actor (the analogue of
        makeShadow; reference: ShadowGraph.java:45-62)."""
        slot = self.slot_of.get(cell)
        if slot is not None:
            return slot
        if not self.free_slots:
            self._grow_nodes()
        slot = self.free_slots.pop()
        self.total_actors_seen += 1
        self.slot_of[cell] = slot
        self.cells[slot] = cell
        self.locations[slot] = cell.system.address
        self.flags[slot] = _F.FLAG_IN_USE  # not interned, not local
        self.recv_count[slot] = 0
        self.supervisor[slot] = -1
        self._touch(slot)
        return slot

    def _touch(self, slot: int) -> None:
        if self._node_log is not None:
            self._node_log.add(slot)

    def _log_pair(self, insert: bool, src: int, dst: int, kind: int) -> None:
        """Record a live-pair transition for the incremental Pallas
        layout; collapse to a full-rebuild sentinel under extreme churn."""
        log = self._pair_log
        if log is None:
            return
        if len(log) >= self._log_cap:
            self._pair_log = None
            return
        log.append((insert, src, dst, kind))

    def _log_pairs_batch(
        self, insert: bool, srcs: np.ndarray, dsts: np.ndarray, kind: int
    ) -> None:
        """Batched :meth:`_log_pair`; collapses to the rebuild sentinel
        when the batch would overflow the log (a sweep that frees a large
        fraction of the graph crosses the layout's repack threshold
        anyway)."""
        log = self._pair_log
        k = len(srcs)
        if log is None or k == 0:
            return
        if len(log) + k > self._log_cap:
            self._pair_log = None
            return
        log.extend(zip([insert] * k, srcs.tolist(), dsts.tolist(), [kind] * k))

    def _update_edge(self, owner: int, target: int, delta: int) -> None:
        """Zero-count edges are deleted (reference: ShadowGraph.java:64-73)."""
        key = (owner << 32) | target
        eid = self.edge_of.get(key)
        if eid is None:
            if delta == 0:
                return
            if not self.free_edges:
                self._grow_edges()
            eid = self.free_edges.pop()
            self.edge_of[key] = eid
            self.edge_src[eid] = owner
            self.edge_dst[eid] = target
            self.edge_weight[eid] = delta
            if delta > 0:
                self._log_pair(True, owner, target, _PAIR_EDGE)
            return
        w_old = self.edge_weight[eid]
        w = w_old + delta
        if w == 0:
            self._free_edge(eid)
        else:
            self.edge_weight[eid] = w
            # The packer layout depends only on edge *liveness* (weight
            # sign), not magnitude; don't invalidate the layout for
            # plain message-count deltas.
            if (w_old > 0) != (w > 0):
                self._log_pair(w > 0, owner, target, _PAIR_EDGE)

    def _free_edge(self, eid: int) -> None:
        owner = int(self.edge_src[eid])
        target = int(self.edge_dst[eid])
        if self.edge_weight[eid] > 0:
            self._log_pair(False, owner, target, _PAIR_EDGE)
        self.edge_of.pop((owner << 32) | target, None)
        self.edge_weight[eid] = 0
        self.free_edges.push(eid)

    def _set_supervisor(self, child_slot: int, new_sup: int) -> None:
        old = int(self.supervisor[child_slot])
        if old == new_sup:
            return
        if old >= 0:
            self._log_pair(False, child_slot, old, _PAIR_SUP)
        if new_sup >= 0:
            self._log_pair(True, child_slot, new_sup, _PAIR_SUP)
        self.supervisor[child_slot] = new_sup

    # ------------------------------------------------------------- #
    # Folding entries (reference: ShadowGraph.java:75-125)
    # ------------------------------------------------------------- #

    def merge_entry(self, entry: Entry) -> None:
        self_slot = self.slot_for(entry.self_ref.target)
        flags = self.flags
        flags[self_slot] |= _F.FLAG_INTERNED | _F.FLAG_LOCAL
        self.recv_count[self_slot] += entry.recv_count
        if entry.is_busy:
            flags[self_slot] |= _F.FLAG_BUSY
        else:
            flags[self_slot] &= ~_F.FLAG_BUSY
        if entry.is_root:
            flags[self_slot] |= _F.FLAG_ROOT
        else:
            flags[self_slot] &= ~_F.FLAG_ROOT
        self._touch(self_slot)

        field_size = self.context.entry_field_size

        for i in range(field_size):
            owner = entry.created_owners[i]
            if owner is None:
                break
            target_slot = self.slot_for(entry.created_targets[i].target)
            owner_slot = self.slot_for(owner.target)
            self._update_edge(owner_slot, target_slot, 1)

        for i in range(field_size):
            child = entry.spawned_actors[i]
            if child is None:
                break
            child_slot = self.slot_for(child.target)
            self._set_supervisor(child_slot, self_slot)

        sm = self.send_matrix
        for i in range(field_size):
            target = entry.updated_refs[i]
            if target is None:
                break
            target_slot = self.slot_for(target.target)
            info = entry.updated_infos[i]
            send_count = refob_info.count(info)
            if send_count > 0:
                self.recv_count[target_slot] -= send_count
                self._touch(target_slot)
                if sm is not None:
                    key = (self_slot << 32) | target_slot
                    sm[key] = sm.get(key, 0) + send_count
            if not refob_info.is_active(info):
                self._update_edge(self_slot, target_slot, -1)

    def merge_entries(self, entries) -> None:
        """Batched fold of a drained entry queue: one pass of Python to
        flatten the object-world entries into slot arrays, then vectorized
        scatter-applies — instead of per-refob field loops per entry
        (reference semantics: ShadowGraph.java:75-125, applied per wake at
        LocalGC.scala:149-177 cadence).

        Equivalent to ``merge_entry`` in queue order: busy/root are
        last-writer-wins per actor, receive counts are commutative sums,
        and edge deltas are aggregated to their per-pair net effect (the
        layout cares only about liveness transitions of the *final* weight
        against the initial one, and intermediate flip-flops fold to
        net no-ops — the same argument slotmap.fold_log documents)."""
        slot_for = self.slot_for
        slot_of_get = self.slot_of.get
        sm = self.send_matrix

        self_slots: List[int] = []
        busyroot: List[int] = []
        recv_deltas: List[int] = []
        ek: List[int] = []  # packed (owner << 32 | target) edge keys
        esign: List[int] = []
        sp_child: List[int] = []
        sp_parent: List[int] = []

        busy = int(_F.FLAG_BUSY)
        root = int(_F.FLAG_ROOT)
        rows_append = self_slots.append
        br_append = busyroot.append
        rd_append = recv_deltas.append
        ek_append = ek.append
        es_append = esign.append

        for entry in entries:
            sc = entry.self_ref._target
            self_slot = slot_of_get(sc)
            if self_slot is None:
                self_slot = slot_for(sc)
            rows_append(self_slot)
            br_append(
                (busy if entry.is_busy else 0) | (root if entry.is_root else 0)
            )
            rd_append(entry.recv_count)

            for owner, target in zip(
                entry.created_owners, entry.created_targets
            ):
                if owner is None:
                    break
                oc = owner._target
                tc = target._target
                os_ = slot_of_get(oc)
                if os_ is None:
                    os_ = slot_for(oc)
                ts = slot_of_get(tc)
                if ts is None:
                    ts = slot_for(tc)
                ek_append((os_ << 32) | ts)
                es_append(1)

            for child in entry.spawned_actors:
                if child is None:
                    break
                cc = child._target
                cs = slot_of_get(cc)
                if cs is None:
                    cs = slot_for(cc)
                sp_child.append(cs)
                sp_parent.append(self_slot)

            for target, info in zip(entry.updated_refs, entry.updated_infos):
                if target is None:
                    break
                tc = target._target
                target_slot = slot_of_get(tc)
                if target_slot is None:
                    target_slot = slot_for(tc)
                send_count = info >> 1
                if send_count > 0:
                    rows_append(target_slot)
                    br_append(-1)  # recv-only row
                    rd_append(-send_count)
                    if sm is not None:
                        key = (self_slot << 32) | target_slot
                        sm[key] = sm.get(key, 0) + send_count
                if info & 1:  # deactivated (refob_info.is_active == False)
                    ek_append((self_slot << 32) | target_slot)
                    es_append(-1)
        self._apply_batch(
            np.asarray(self_slots, dtype=np.int64),
            np.asarray(busyroot, dtype=np.int64),
            np.asarray(recv_deltas, dtype=np.int64),
            np.asarray(ek, dtype=np.int64),
            np.asarray(esign, dtype=np.int64),
            np.asarray(sp_child, dtype=np.int64),
            np.asarray(sp_parent, dtype=np.int64),
        )

    def _apply_batch(
        self,
        sl: np.ndarray,
        br: np.ndarray,
        rd: np.ndarray,
        ek: np.ndarray,
        esign: np.ndarray,
        sp_child: np.ndarray,
        sp_parent: np.ndarray,
        sl_seq: Optional[np.ndarray] = None,
        sp_seq: Optional[np.ndarray] = None,
    ) -> None:
        """The vectorized scatter-applies shared by both fold planes
        (object entries and packed rows).

        ``sl``/``br``/``rd`` run in queue order; rows with ``br == -1``
        are recv-only (no busy/root write).  ``ek``/``esign`` are packed
        ``owner << 32 | target`` edge keys with signs, order-free (only
        net deltas matter).  ``sp_child``/``sp_parent`` run in queue
        order (last writer wins a child's supervisor).

        ``sl_seq``/``sp_seq`` (packed plane only): global flush stamps
        for the last-writer-wins writes.  Per-thread rings drain
        independently, so a LATER batch can carry an EARLIER flush of
        the same actor — the stamps let the graph refuse stale busy/
        root/supervisor writes across batches.  Additive facts (recv
        sums, interning, net edge deltas) commute and need no guard.
        The object plane passes None: its single FIFO queue already
        totally orders flushes."""
        if sl.size:
            np.add.at(self.recv_count, sl, rd)
            selfrows = br >= 0
            ssl = sl[selfrows]
            sbr = br[selfrows]
            # Last entry wins busy/root: unique() on the reversed slot
            # array returns each slot's first reversed occurrence = its
            # last occurrence in queue order.
            u, ridx = np.unique(ssl[::-1], return_index=True)
            last_bits = sbr[::-1][ridx].astype(np.uint8)
            f = self.flags
            interned = np.uint8(int(_F.FLAG_INTERNED) | int(_F.FLAG_LOCAL))
            keep = np.uint8(0xFF & ~(int(_F.FLAG_BUSY) | int(_F.FLAG_ROOT)))
            if sl_seq is not None:
                seqs = sl_seq[selfrows][::-1][ridx]
                fresh = seqs >= self._br_seq[u]
                self._br_seq[u[fresh]] = seqs[fresh]
                # Interning is monotone — applies even for stale rows.
                f[u] |= interned
                uf = u[fresh]
                f[uf] = (f[uf] & keep) | last_bits[fresh]
            else:
                f[u] = ((f[u] | interned) & keep) | last_bits
            if self._node_log is not None:
                self._node_log.update(sl.tolist())

        if sp_child.size:
            u, ridx = np.unique(sp_child[::-1], return_index=True)
            newp = sp_parent[::-1][ridx]
            if sp_seq is not None:
                seqs = sp_seq[::-1][ridx]
                fresh = seqs >= self._sup_seq[u]
                self._sup_seq[u[fresh]] = seqs[fresh]
                u, newp = u[fresh], newp[fresh]
            old = self.supervisor[u].astype(np.int64)
            changed = old != newp
            uu, oo, nn = u[changed], old[changed], newp[changed]
            has_old = oo >= 0
            self._log_pairs_batch(False, uu[has_old], oo[has_old], _PAIR_SUP)
            self._log_pairs_batch(True, uu, nn, _PAIR_SUP)
            self.supervisor[uu] = nn

        if ek.size:
            u, inv = np.unique(ek, return_inverse=True)
            delta = np.zeros(u.size, dtype=np.int64)
            np.add.at(delta, inv, esign)
            nz = delta != 0
            self._apply_edge_deltas(u[nz], delta[nz])

    # ------------------------------------------------------------- #
    # Packed-plane fold (packed.py row layout)
    # ------------------------------------------------------------- #

    def attach_packed_plane(self, plane, resolve_cell) -> None:
        """Wire the engine's packed plane in: ``plane.uid_strong`` pins
        cells named by in-flight rows; ``resolve_cell`` (the system's
        weak uid registry) is the fallback for uids whose pin was
        already consumed."""
        self._plane = plane
        self._resolve_cell = resolve_cell

    def _slots_for_uids(self, uids: np.ndarray) -> np.ndarray:
        """Map uids -> slots through the dense array, interning unseen
        uids (the only per-item Python in the packed fold, bounded by
        the spawn rate rather than the flush rate).

        An unresolvable uid maps to -1 and the caller drops the fields
        naming it.  That is sound, not lossy: a uid resolves through
        the plane's strong pin (held from flush until the actor's slot
        is swept) or the system's weak registry (hit for any cell the
        runtime still references, i.e. every live actor), so
        unresolvable means the collector already PROVED the actor
        garbage and swept it — and garbage is monotone, so late facts
        about it (receive deltas, deactivations, edges) change nothing
        the sweep has not already settled."""
        m = self._uid_to_slot
        maxu = int(uids.max(initial=0))
        if maxu >= m.shape[0]:
            grown = max(m.shape[0] * 2, maxu + 1)
            m = np.concatenate(
                [m, np.full(grown - m.shape[0], -1, dtype=np.int64)]
            )
            self._uid_to_slot = m
        slots = m[uids]
        missing = slots < 0
        if missing.any():
            us = self._plane.uid_strong
            resolve = self._resolve_cell
            for uid in np.unique(uids[missing]).tolist():
                cell = us.get(uid)
                if cell is None:
                    cell = resolve(uid)
                    if cell is None:
                        continue  # proven-garbage uid: fields dropped
                slot = self.slot_for(cell)
                m[uid] = slot
                self._slot_uid[slot] = uid
            slots = m[uids]
        return slots

    def merge_packed(self, rows: np.ndarray) -> None:
        """Fold a drained batch of packed rows: restore global flush
        order from the seq column, map uids to slots, and run the same
        vectorized scatter-applies as the object plane — semantically
        ``merge_entry`` per row, in seq order, with flush stamps
        guarding cross-batch staleness (see _apply_batch) and fields
        naming proven-garbage uids dropped (see _slots_for_uids)."""
        E = self.context.entry_field_size
        order = np.argsort(rows[:, 0], kind="stable")
        R = rows[order]

        self_slots = self._slots_for_uids(R[:, 1])
        c0 = 4

        # Created (owner,target) pairs are extracted BEFORE the
        # self-uid keep filter: the facts name only the owner and the
        # target, not the flushing actor, so an unresolvable flusher
        # must not drop edges between two other, still-live actors —
        # an under-counted live edge is exactly the over-collection
        # hazard the soundness invariant forbids (ADVICE r5).
        created = R[:, c0 : c0 + 2 * E]
        ow = created[:, 0::2].ravel()
        tg = created[:, 1::2].ravel()
        vc = ow >= 0
        ow, tg = ow[vc], tg[vc]
        ow_s = self._slots_for_uids(ow) if ow.size else ow
        tg_s = self._slots_for_uids(tg) if tg.size else tg
        cok = (ow_s >= 0) & (tg_s >= 0)
        ow_s, tg_s = ow_s[cok], tg_s[cok]

        if (self_slots < 0).any():
            # Only the flusher's OWN facts (self state, recv delta,
            # spawned children, updated refobs) drop with it.
            keep = self_slots >= 0
            R = R[keep]
            self_slots = self_slots[keep]
        seq = R[:, 0]
        bits = R[:, 2]
        recv = R[:, 3]
        spawned = R[:, c0 + 2 * E : c0 + 3 * E]
        upd = R[:, c0 + 3 * E : c0 + 5 * E]

        sp = spawned.ravel()
        vs = sp >= 0
        sp_s = self._slots_for_uids(sp[vs]) if vs.any() else sp[vs]
        sp_parent = np.repeat(self_slots, E)[vs]
        sp_seq = np.repeat(seq, E)[vs]
        sok = sp_s >= 0
        sp_s, sp_parent, sp_seq = sp_s[sok], sp_parent[sok], sp_seq[sok]

        ut = upd[:, 0::2].ravel()
        ui = upd[:, 1::2].ravel()
        vu = ut >= 0
        ut_s = self._slots_for_uids(ut[vu]) if vu.any() else ut[vu]
        uok = ut_s >= 0
        ut_s = ut_s[uok]
        uiv = ui[vu][uok]
        upd_self = np.repeat(self_slots, E)[vu][uok]

        # busy/root bit pairs -> flag bytes
        lb = np.array(
            [
                0,
                int(_F.FLAG_BUSY),
                int(_F.FLAG_ROOT),
                int(_F.FLAG_BUSY) | int(_F.FLAG_ROOT),
            ],
            dtype=np.int64,
        )
        br = lb[bits & 3]

        send = uiv >> 1
        has_send = send > 0
        deact = (uiv & 1) == 1

        sm = self.send_matrix
        if sm is not None and has_send.any():
            skeys = (upd_self[has_send] << 32) | ut_s[has_send]
            for key, count in zip(skeys.tolist(), send[has_send].tolist()):
                sm[key] = sm.get(key, 0) + count

        sl = np.concatenate([self_slots, ut_s[has_send]])
        brr = np.concatenate([br, np.full(int(has_send.sum()), -1, np.int64)])
        rdd = np.concatenate([recv, -send[has_send]])
        sl_seq = np.concatenate([seq, np.zeros(int(has_send.sum()), np.int64)])

        ek = np.concatenate(
            [(ow_s << 32) | tg_s, (upd_self[deact] << 32) | ut_s[deact]]
        )
        esign = np.concatenate(
            [
                np.ones(ow_s.size, dtype=np.int64),
                np.full(int(deact.sum()), -1, dtype=np.int64),
            ]
        )

        self._apply_batch(
            sl, brr, rdd, ek, esign, sp_s, sp_parent,
            sl_seq=sl_seq, sp_seq=sp_seq,
        )

    def _apply_edge_deltas(self, keys: np.ndarray, deltas: np.ndarray) -> None:
        """Vectorized ``_update_edge`` over unique packed keys with
        nonzero net deltas: bulk id allocation, array scatter, batch dict
        update, and batched liveness-transition logging."""
        eo = self.edge_of
        eids = eo.get_batch(keys)
        existing = eids >= 0

        ex_eids = eids[existing]
        if ex_eids.size:
            w = self.edge_weight
            ex_keys = keys[existing]
            w_old = w[ex_eids]
            w_new = w_old + deltas[existing]
            live_old = w_old > 0
            live_new = w_new > 0
            went_live = ~live_old & live_new
            went_dead = live_old & ~live_new
            if went_live.any():
                self._log_pairs_batch(
                    True,
                    ex_keys[went_live] >> 32,
                    ex_keys[went_live] & 0xFFFFFFFF,
                    _PAIR_EDGE,
                )
            if went_dead.any():
                self._log_pairs_batch(
                    False,
                    ex_keys[went_dead] >> 32,
                    ex_keys[went_dead] & 0xFFFFFFFF,
                    _PAIR_EDGE,
                )
            w[ex_eids] = w_new
            freed = w_new == 0
            if freed.any():
                fr = ex_eids[freed]
                w[fr] = 0
                self.free_edges.push_batch(fr)
                eo.pop_batch(ex_keys[freed])

        new_keys = keys[~existing]
        if new_keys.size:
            d_new = deltas[~existing]
            need = int(new_keys.size)
            if len(self.free_edges) < need:
                self._grow_edges(min_free=need)
            aa = self.free_edges.pop_batch(need)
            self.edge_src[aa] = (new_keys >> 32).astype(np.int32)
            self.edge_dst[aa] = (new_keys & 0xFFFFFFFF).astype(np.int32)
            self.edge_weight[aa] = d_new
            eo.put_batch_new(new_keys, aa)
            pos = d_new > 0
            if pos.any():
                self._log_pairs_batch(
                    True,
                    new_keys[pos] >> 32,
                    new_keys[pos] & 0xFFFFFFFF,
                    _PAIR_EDGE,
                )

    def merge_delta(self, delta) -> None:
        """Fold a peer node's compressed batch
        (reference: ShadowGraph.java:127-156)."""
        decoder = delta.decoder()
        slots = [self.slot_for(cell) for cell in decoder]
        for i, delta_shadow in enumerate(delta.shadows):
            slot = slots[i]
            if delta_shadow.interned:
                self.flags[slot] |= _F.FLAG_INTERNED
                if delta_shadow.is_busy:
                    self.flags[slot] |= _F.FLAG_BUSY
                else:
                    self.flags[slot] &= ~_F.FLAG_BUSY
                if delta_shadow.is_root:
                    self.flags[slot] |= _F.FLAG_ROOT
                else:
                    self.flags[slot] &= ~_F.FLAG_ROOT
            self.recv_count[slot] += delta_shadow.recv_count
            self._touch(slot)
            if delta_shadow.supervisor >= 0:
                self._set_supervisor(slot, slots[delta_shadow.supervisor])
            for target_id, count in delta_shadow.outgoing.items():
                self._update_edge(slot, slots[target_id], count)

    def merge_undo_log(self, log) -> None:
        """Halt a dead node's actors and revert its unadmitted effects
        (reference: ShadowGraph.java:158-174).

        The worklist grows while folding: applying admitted created-refs
        can intern previously-unknown target actors, and those must also
        be visited (halted if they lived on the dead node) — the oracle
        gets this by iterating its live from_set list, which visits
        shadows appended mid-fold."""
        cells = list(self.slot_of.keys())
        seen = set(cells)
        i = 0
        while i < len(cells):
            cell = cells[i]
            i += 1
            slot = self.slot_of[cell]
            if self.locations[slot] == log.node_address:
                self.flags[slot] |= _F.FLAG_HALTED
                self._touch(slot)
            field = log.admitted.get(cell)
            if field is not None:
                self.recv_count[slot] += field.message_count
                self._touch(slot)
                for target_cell, count in field.created_refs.items():
                    if target_cell not in seen:
                        seen.add(target_cell)
                        cells.append(target_cell)
                    self._update_edge(slot, self.slot_for(target_cell), count)

    # ------------------------------------------------------------- #
    # Trace + sweep (reference: ShadowGraph.java:205-289)
    # ------------------------------------------------------------- #

    def compute_marks(self) -> np.ndarray:
        if self.use_device:
            with events.recorder.timed(events.DEVICE_TRACE) as ev:
                if self.decremental:
                    return _readback(
                        self._compute_marks_decremental(ev),
                        "marks.decremental",
                    )
                if self._on_tpu():
                    return _readback(
                        self._compute_marks_pallas(ev), "marks.pallas"
                    )
                return _readback(
                    trace_ops.trace_marks_jax(
                        self.flags,
                        self.recv_count,
                        self.supervisor,
                        self.edge_src,
                        self.edge_dst,
                        self.edge_weight,
                    ),
                    "marks.xla",
                )
        # Host path: slice to the occupancy watermark.  Slots allocate
        # lowest-first (IntStack from_range), so live slots cluster low
        # and the 12-sweep fixpoint need not scan the grown capacity —
        # two O(capacity) scans here replace O(capacity) work in every
        # sweep.  Safe: flags beyond the last in-use slot are zero, and
        # every nonzero-weight edge/supervisor references in-use slots.
        nz = np.flatnonzero(self.flags)
        h = int(nz[-1]) + 1 if nz.size else 0
        enz = np.flatnonzero(self.edge_weight)
        eh = int(enz[-1]) + 1 if enz.size else 0
        mark = np.zeros(self.capacity, dtype=bool)
        if h:
            mark[:h] = trace_ops.trace_marks_np(
                self.flags[:h],
                self.recv_count[:h],
                self.supervisor[:h],
                self.edge_src[:eh],
                self.edge_dst[:eh],
                self.edge_weight[:eh],
            )
        return mark

    def _compute_marks_with_parents(self) -> np.ndarray:
        """Mark fixpoint with why-live parent capture: stores the
        (mark, parent) pair on ``last_parents``/``last_parents_mark``
        and returns the marks.  Marks are bit-identical to
        :meth:`compute_marks` (parity-tested against both kernels), so
        the sweep that consumes them is unchanged.  The device form is
        one extra XLA fixpoint (ops/pallas_trace.py marking_parents_jax
        — the mark MXU kernel cannot attribute sources); the host form
        is the numpy scatter-min twin.  Reached only when
        ``capture_parents`` was set for this wake."""
        if self.use_device:
            from ...ops import pallas_trace as _pt

            with events.recorder.timed(events.DEVICE_TRACE) as ev:
                ev.fields["trace_mode"] = self.trace_mode
                ev.fields["capture_parents"] = True
                mark, parent = _pt.marking_parents_jax(
                    self.flags,
                    self.recv_count,
                    self.supervisor,
                    self.edge_src,
                    self.edge_dst,
                    self.edge_weight,
                )
                mark = _readback(mark, "marks.parents")
                parent = _readback(parent, "parents.capture")
        else:
            mark, parent = trace_ops.trace_marks_np_parents(
                self.flags,
                self.recv_count,
                self.supervisor,
                self.edge_src,
                self.edge_dst,
                self.edge_weight,
            )
        # Both branches materialized host arrays above (the device one
        # through the accounted _readback), so these are plain aliases.
        self.last_parents = parent
        self.last_parents_mark = mark
        return mark

    def _on_tpu(self) -> bool:
        tpu = getattr(self, "_is_tpu", None)
        if tpu is None:
            from ...ops import pallas_trace

            tpu = self._is_tpu = not pallas_trace.default_interpret()
        return tpu

    def _stamp_sweep_stats(self, ev, stats: Optional[dict]) -> None:
        """Attach the fixpoint's per-sweep frontier decomposition to the
        enclosing DEVICE_TRACE event — the wake profiler
        (telemetry/profile.py) carries these fields into its per-wake
        records, which is where the pull-density threshold is tuned
        from data (tools/sweep_profile.py reads the same shapes)."""
        ev.fields["trace_mode"] = self.trace_mode
        if stats is None:
            return
        k = int(stats["n_sweeps"])
        ev.fields["n_sweeps"] = k
        k = min(k, len(stats["dirty_chunks"]))
        ev.fields["sweep_dirty_chunks"] = stats["dirty_chunks"][:k].tolist()
        if "changed_supers" in stats:
            ev.fields["sweep_changed_supers"] = (
                stats["changed_supers"][:k].tolist()
            )
        ev.fields["sweep_tiles_skipped"] = stats["tiles_skipped"][:k].tolist()
        ev.fields["sweep_pull_on"] = stats["pull_on"][:k].tolist()

    def _compute_marks_pallas(self, ev=None) -> np.ndarray:
        """Device trace through the Pallas propagation kernel.

        Layout maintenance is incremental (ops/pallas_incremental.py):
        pair transitions recorded in ``_pair_log`` are folded into the
        cached base+delta layout in O(changes), so a churning graph no
        longer pays a full O(E log E) repack before every wake.  A full
        rebuild happens only on node-capacity growth, log overflow, or
        when accumulated churn crosses the layout's repack threshold."""
        from ...ops import pallas_incremental

        self._inc = self._sync_layout(
            self._inc,
            lambda: pallas_incremental.IncrementalPallasLayout(
                self.capacity,
                mode=self.trace_mode,
                pull_density=self.pull_density,
            ),
            lambda l: l.needs_repack,
        )
        if ev is not None and self.sweep_stats:
            marks, stats = self._inc.trace(
                self.flags, self.recv_count, with_stats=True
            )
            self._stamp_sweep_stats(ev, stats)
            return marks
        return self._inc.trace(self.flags, self.recv_count)

    def _sync_layout(self, obj, make, needs_repack) -> object:
        """The pair-log consumption state machine shared by the Pallas
        and decremental paths: (re)build on a missing object, geometry
        change, or log overflow (``_pair_log is None``); otherwise fold
        the log and repack when accumulated churn crosses the layout's
        threshold.  Returns the up-to-date object."""
        if obj is None or self._pair_log is None:
            if obj is None or obj.n != self.capacity:
                obj = make()
            obj.rebuild(
                self.edge_src, self.edge_dst, self.edge_weight, self.supervisor
            )
            self._pair_log = []
        elif self._pair_log:
            obj.apply_log(self._pair_log)
            self._pair_log.clear()
            if needs_repack(obj):
                obj.rebuild(
                    self.edge_src,
                    self.edge_dst,
                    self.edge_weight,
                    self.supervisor,
                )
        return obj

    def _compute_marks_decremental(self, ev=None) -> np.ndarray:
        """Per-wake detection through the decremental tracer: the wake
        cost is proportional to the churn's affected region, not the
        graph (ops/pallas_decremental.py; the steady-state analogue of
        the reference's 50ms incremental collect, LocalGC.scala:144-186,
        at scales where a full re-trace cannot meet the cadence)."""
        self._dec = self._synced_dec()
        self._dec.collect_stats = ev is not None and self.sweep_stats
        try:
            marks = self._dec.marks(self.flags, self.recv_count)
            if self._dec.collect_stats:
                ls = self._dec.last_stats
                self._stamp_sweep_stats(
                    ev,
                    None if ls is None else {
                        k: np.asarray(v)  # readback: sweep-stat words
                        for k, v in ls.items()
                    },
                )
            return marks
        except Exception:
            # A poisoned async result surfaces at the readback inside
            # marks(), after the tracer committed state; drop it so the
            # next wake re-derives instead of feeding poisoned arrays.
            self._dec.invalidate()
            raise

    # ------------------------------------------------------------- #
    # Pipelined collection (SURVEY §7 "hard parts": the 50ms cadence
    # can't meet a 10ms detection budget without overlapping host
    # ingest and the device trace).  launch_trace() snapshots the node
    # features and dispatches the device wake asynchronously;
    # harvest_trace() later sweeps with the SNAPSHOT verdicts.  Sound
    # because CRGC garbage is monotone: an actor unreachable and
    # quiescent at any consistent snapshot can never be resurrected
    # (only garbage held references to it), so acting on a stale
    # verdict kills nothing live — and slots are freed only by the
    # harvest itself, so the snapshot's slot bindings still hold.
    # ------------------------------------------------------------- #

    _pending_wake = None

    @property
    def can_pipeline(self) -> bool:
        return self.use_device and self.decremental

    @property
    def has_pending_wake(self) -> bool:
        return self._pending_wake is not None

    def _synced_dec(self):
        """The decremental tracer, synced with the pair log (the one
        construction site for both the synchronous and pipelined
        paths)."""
        from ...ops import pallas_decremental

        self._dec = self._sync_layout(
            self._dec,
            lambda: pallas_decremental.DecrementalTracer(
                self.capacity,
                mode=self.trace_mode,
                pull_density=self.pull_density,
            ),
            lambda d: d.layout.needs_repack,
        )
        return self._dec

    def _start_wake(self) -> tuple:
        """Dispatch one asynchronous wake; returns ``(handle,
        mark_dev)`` where the handle provides ``unpack_marks`` /
        ``invalidate`` (the contract harvest_trace and
        expire_stalled_wake consume).  Overridable: the mesh backend
        dispatches its sharded wake here, while the snapshot and
        bookkeeping stay in :meth:`launch_trace` — one home for the
        pending-wake tuple layout."""
        import jax

        dec = self._synced_dec()
        return dec, dec.wake_device(
            jax.device_put(self.flags), jax.device_put(self.recv_count)
        )

    def launch_trace(self) -> None:
        """Dispatch the device wake without waiting for its result.
        No-op while a wake is already in flight."""
        import time

        if self._pending_wake is not None:
            return
        handle, mark_dev = self._start_wake()
        self._pending_wake = (
            handle,
            mark_dev,
            self.flags.copy(),
            self.supervisor.copy(),
            time.monotonic(),
        )

    def harvest_ready(self) -> bool:
        if self._pending_wake is None:
            return False
        mark_w = self._pending_wake[1]
        is_ready = getattr(mark_w, "is_ready", None)
        return bool(is_ready()) if is_ready is not None else True

    def expire_stalled_wake(self, max_age_s: float) -> bool:
        """A wake whose device result never lands (wedged transport)
        must not deadlock the pipeline: past ``max_age_s`` the pending
        wake is abandoned and the tracer invalidated, so the next wake
        is a clean full re-derivation.  Returns True if expired."""
        import time

        if self._pending_wake is None:
            return False
        dec, _, _, _, t0 = self._pending_wake
        if time.monotonic() - t0 < max_age_s:
            return False
        self._pending_wake = None
        dec.invalidate()
        return True

    def harvest_trace(self, should_kill: bool) -> int:
        """Sweep with the pending wake's verdicts against its snapshot.
        Returns the number of garbage actors (0 if nothing pending)."""
        if self._pending_wake is None:
            return 0
        dec, mark_w, snap_flags, snap_sup, _ = self._pending_wake
        self._pending_wake = None
        with events.recorder.timed(events.TRACING) as ev:
            # unpack_marks auto-invalidates the tracer on readback
            # failure, so a poisoned wake needs no handling here.
            if getattr(dec, "accounts_readback", False):
                # The handle already routed the crossing through
                # _readback (the mesh wake handle does, under its
                # collective lock) — accounting it again here would
                # double-count every harvested wake's transfer bytes.
                mark = np.asarray(dec.unpack_marks(mark_w))  # readback: accounted in the handle
            else:
                mark = _readback(dec.unpack_marks(mark_w), "marks.harvest")
            with events.recorder.timed(events.SWEEP):
                garbage, kill = trace_ops.garbage_and_kills_np(
                    snap_flags, snap_sup, mark
                )
                if garbage.shape[0] < self.capacity:
                    # capacity grew between launch and harvest: slots beyond
                    # the snapshot were interned after it, so they carry no
                    # verdict (not garbage) — pad so the sweep's edge scans
                    # index the grown arrays safely
                    pad = np.zeros(self.capacity - garbage.shape[0], bool)
                    garbage = np.concatenate([garbage, pad])
                    kill = np.concatenate([kill, pad])
                garbage_slots = np.nonzero(garbage)[0]
                kill_slots = np.nonzero(kill)[0]
                if should_kill and kill_slots.size:
                    self._kill_slots_bulk(kill_slots)
                if garbage_slots.size:
                    self._free_slots_batch(garbage, garbage_slots)
            ev.fields["num_garbage_actors"] = int(garbage_slots.size)
            ev.fields["num_live_actors"] = int(np.count_nonzero(mark))
        return int(garbage_slots.size)

    def trace(self, should_kill: bool) -> int:
        # A synchronous trace sweeps against CURRENT state; an
        # unharvested pipelined wake would later sweep a snapshot whose
        # slot bindings this sweep is about to invalidate (freed or
        # re-interned slots) — discard it.  Nothing is lost: the fresh
        # verdicts computed here are a superset of the snapshot's
        # (garbage is monotone).
        self._pending_wake = None
        with events.recorder.timed(events.TRACING) as ev:
            if self.capture_parents:
                mark = self._compute_marks_with_parents()
            else:
                mark = self.compute_marks()
            # The sweep (kill decisions + slot frees) nests in its own
            # timed event so the wake profiler can attribute
            # trace-vs-sweep time (telemetry/profile.py).
            with events.recorder.timed(events.SWEEP):
                garbage, kill = trace_ops.garbage_and_kills_np(
                    self.flags, self.supervisor, mark
                )
                garbage_slots = np.nonzero(garbage)[0]
                kill_slots = np.nonzero(kill)[0]

                if should_kill and kill_slots.size:
                    self._kill_slots_bulk(kill_slots)

                if garbage_slots.size:
                    self._free_slots_batch(garbage, garbage_slots)

            ev.fields["num_garbage_actors"] = int(garbage_slots.size)
            ev.fields["num_live_actors"] = int(np.count_nonzero(mark))
        return int(garbage_slots.size)

    def _kill_slots_bulk(self, kill_slots: np.ndarray) -> None:
        """Send StopMsg to every kill slot's cell as ONE bulk teardown:
        the finalize cascade is batched per dispatcher (and, for remote
        cells, per peer writer), so a wake that kills K actors costs
        O(batches) dispatcher operations, not O(K)."""
        from ...runtime.cell import tell_bulk

        cells = self.cells
        tell_bulk((cells[slot], StopMsg) for slot in kill_slots.tolist())

    def _free_slots_batch(
        self, garbage: np.ndarray, garbage_slots: np.ndarray
    ) -> None:
        """Free every garbage slot in one vectorized pass (the sweep,
        reference: ShadowGraph.java:273-289).

        Incident edges are found by scanning the flat edge arrays — an
        edge is allocated iff its weight is nonzero — instead of per-slot
        incident sets, so the sweep is O(edge capacity) numpy + O(dead
        edges) dict deletions rather than Python set surgery per slot.

        Supervisor pointers *into* a garbage slot need no scan: a live,
        non-halted child marks its supervisor, so the pointing node is
        garbage in the same sweep and its pointer is cleared here too."""
        w = self.edge_weight
        em = (w != 0) & (garbage[self.edge_src] | garbage[self.edge_dst])
        eids = np.nonzero(em)[0]
        if eids.size:
            srcs = self.edge_src[eids]
            dsts = self.edge_dst[eids]
            live = w[eids] > 0
            self._log_pairs_batch(False, srcs[live], dsts[live], _PAIR_EDGE)
            eo = self.edge_of
            if eids.size * 2 > len(eo):
                # Most edges die: rebuild the key map from the survivors
                # in one pass instead of popping each dead key.
                w[eids] = 0
                alive = np.nonzero(w != 0)[0]
                keys = (self.edge_src[alive].astype(np.int64) << 32) | (
                    self.edge_dst[alive]
                )
                self.edge_of = I64Map.build(keys, alive)
            else:
                eo.pop_batch((srcs.astype(np.int64) << 32) | dsts)
                w[eids] = 0
            self.free_edges.push_batch(eids)

        sup = self.supervisor[garbage_slots]
        has_sup = sup >= 0
        self._log_pairs_batch(
            False, garbage_slots[has_sup], sup[has_sup], _PAIR_SUP
        )
        self.supervisor[garbage_slots] = -1
        self.flags[garbage_slots] = 0
        self.recv_count[garbage_slots] = 0

        # Invalidate packed-plane uid mappings and drop the strong pins
        # for freed slots.  A proven-garbage actor can never matter
        # again (CRGC garbage is monotone), so any later row naming its
        # uid is droppable — _slots_for_uids handles the unresolvable
        # case.  Slot reuse also resets the flush-stamp guards.
        su = self._slot_uid
        freed_uids = su[garbage_slots]
        had_uid = freed_uids >= 0
        if had_uid.any():
            self._uid_to_slot[freed_uids[had_uid]] = -1
            su[garbage_slots] = -1
            if self._plane is not None:
                pop = self._plane.uid_strong.pop
                for uid in freed_uids[had_uid].tolist():
                    pop(uid, None)
        self._br_seq[garbage_slots] = -1
        self._sup_seq[garbage_slots] = -1

        sm = self.send_matrix
        if sm:
            # Traffic rows naming a swept slot die with it: a freed slot
            # may re-intern a different actor, and a proven-garbage
            # actor's history is useless to placement.
            dead_keys = [
                key
                for key in sm
                if garbage[key >> 32] or garbage[key & 0xFFFFFFFF]
            ]
            for key in dead_keys:
                del sm[key]

        cells = self.cells
        locations = self.locations
        slot_of = self.slot_of
        slots_list = garbage_slots.tolist()
        for slot in slots_list:
            cell = cells[slot]
            if cell is not None:
                slot_of.pop(cell, None)
                cells[slot] = None
            locations[slot] = None
        self.free_slots.push_batch(garbage_slots)
        if self._node_log is not None:
            self._node_log.update(slots_list)

    # ------------------------------------------------------------- #
    # Waves (reference: ShadowGraph.java:291-299)
    # ------------------------------------------------------------- #

    def start_wave(self) -> int:
        flags = self.flags
        rootmask = (
            ((flags & _F.FLAG_ROOT) != 0)
            & ((flags & _F.FLAG_LOCAL) != 0)
            & ((flags & _F.FLAG_IN_USE) != 0)
        )
        count = 0
        for slot in np.nonzero(rootmask)[0]:
            cell = self.cells[slot]
            if cell is not None:
                count += 1
                cell.tell(WaveMsg)
        return count

    # ------------------------------------------------------------- #
    # Diagnostics
    # ------------------------------------------------------------- #

    @property
    def num_in_use(self) -> int:
        return len(self.slot_of)

    def addresses_in_graph(self) -> Dict[str, int]:
        """Uncollected shadows per node address
        (reference: ShadowGraph.java:331-340, structured instead of
        printed)."""
        counts: Dict[str, int] = {}
        for slot in self.slot_of.values():
            loc = self.locations[slot]
            counts[loc] = counts.get(loc, 0) + 1
        return counts

    def investigate_live_set(self) -> Dict[str, object]:
        """Structured dump of the live set, vectorized over the slot
        arrays (reference: ShadowGraph.java:342-394; same fields as the
        oracle's implementation, differentially tested)."""
        from .shadow import _cell_path

        slots = np.fromiter(
            self.slot_of.values(), np.int64, len(self.slot_of)
        )
        f = self.flags[slots]
        local = (f & _F.FLAG_LOCAL) != 0
        root_slots = slots[(f & _F.FLAG_ROOT) != 0]

        # an edge exists iff weight != 0 (negative = more deactivations
        # seen than creations so far), matching the oracle's outgoing map
        eids = np.nonzero(self.edge_weight != 0)[0]
        esrc = self.edge_src[eids]
        edst = self.edge_dst[eids]
        ew = self.edge_weight[eids]
        out_degree = np.bincount(esrc, minlength=self.capacity)
        local_all = (self.flags & _F.FLAG_LOCAL) != 0
        src_local = local_all[esrc]
        dst_local = local_all[edst]
        ltr = np.nonzero(src_local & ~dst_local)[0]
        local_to_remote = sorted(
            (
                _cell_path(self.cells[int(esrc[e])]),
                _cell_path(self.cells[int(edst[e])]),
                int(ew[e]),
            )
            for e in ltr.tolist()
        )
        return {
            "total": int(slots.size),
            "non_interned": int((~((f & _F.FLAG_INTERNED) != 0)).sum()),
            "roots": int(root_slots.size),
            "busy": int(((f & _F.FLAG_BUSY) != 0).sum()),
            "nonzero_recv": int((self.recv_count[slots] != 0).sum()),
            "nonlocal": int((~local).sum()),
            "root_acquaintances": {
                _cell_path(self.cells[int(s)]): int(out_degree[int(s)])
                for s in root_slots.tolist()
            },
            "local_to_remote": local_to_remote,
            "remote_to_local_count": int((~src_local & dst_local).sum()),
        }

    def count_reachable_from(self, address: str) -> int:
        """(reference: ShadowGraph.java:302-330)"""
        seed = np.zeros(self.capacity, dtype=bool)
        for cell, slot in self.slot_of.items():
            if self.locations[slot] == address:
                seed[slot] = True
        halted = (self.flags & _F.FLAG_HALTED) != 0
        live_edge = self.edge_weight > 0
        esrc = self.edge_src[live_edge]
        edst = self.edge_dst[live_edge]
        mark = seed
        while True:
            active = mark & ~halted
            new_mark = mark.copy()
            if esrc.size:
                new_mark[edst[active[esrc]]] = True
            new_mark &= (self.flags & _F.FLAG_IN_USE) != 0
            new_mark |= mark
            if np.array_equal(new_mark, mark):
                return int(np.count_nonzero(mark))
            mark = new_mark
