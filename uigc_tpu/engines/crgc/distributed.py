"""The distributed collector: shadow graph sharded ACROSS cluster nodes.

The reference (and the replicated multi-node mode in collector.py)
gives every node a FULL shadow-graph replica: each collector folds every
peer's delta broadcast and traces the whole graph, capping the
collector at what one host holds — the wall ROADMAP item 2 names.  This
module is the other end-state: each node owns only the shadow-graph
slice for the partitions it owns (parallel/partition.py — the SAME
rendezvous family as the PR 4 ShardTable, so entity placement and
shadow partitioning never fight), and cross-node garbage is found by a
coordinator-free trace-wave protocol:

- **Routing**: a mutator entry's effects are split per affected actor
  and folded into per-owner delta graphs (delta.py ``fold_*``): flags +
  receive balance to the actor's owner, edges to the SOURCE actor's
  owner, supervisor pointers to the CHILD's owner, bare mentions to a
  created target's owner.  Deltas ride the fabric point-to-point to the
  one owner instead of broadcasting to everyone.
- **Trace waves**: each wave runs the local fixpoint over the owned
  slice only; marks that reach a *mirror* (an edge endpoint owned
  elsewhere) leave as cumulative ``dmark`` frames to the owner, which
  folds them as seeds and continues — so cross-node cycles iterate to
  the same global fixpoint the single-host trace computes.  Mark sets
  are cumulative per wave and re-sent until acked (``dmack``), so
  dropped/duplicated/reordered frames cannot corrupt or stall a wave.
- **Termination**: a Safra-style round — (settled, changed-since-last,
  sent, received) — aggregates leaf-to-root over the deterministic
  reduction tree (parallel/partition.py ``ReductionTree``, the
  Tascade-shaped asynchronous reduction of PAPERS.md); two consecutive
  clean rounds prove the global fixpoint and the root broadcasts
  ``dfin``.  No coordinator process, no per-wave full-graph allgather —
  the tree root is just the lowest live address and re-derives itself
  from membership.
- **Sweep**: each owner sweeps its own slice.  The kill gate (only the
  oldest unmarked ancestor is stopped; its stop cascades) needs the
  supervisor's authoritative mark, which may live on another node: a
  ``dgate`` query asks the supervisor's owner, which dispatches the
  StopMsg itself when the supervisor is live.  Unacked gates re-dirty
  the graph so the next wave retries — a lost frame can only DELAY a
  collection, never kill a live actor.
- **Absorb on death**: every node retains, per partition, a cumulative
  delta journal of the facts it generated.  When a member dies, the
  fence bumps, ownership remaps (rendezvous: only the dead node's
  partitions move), survivors re-send their journals for the moved
  partitions to the new owners, and the new owner re-folds from a reset
  slice — the dead node's own facts die with it, which (like a skipped
  undo fold) can only LEAK, never collect a live actor.  The existing
  undo-log quorum then halts the dead node's actors and reverts its
  unadmitted claims, restricted per node to the slice it owns.

Two sharding levels coexist: the mesh backend keeps sharding the
fold/trace across local devices *within* a node, and this layer shards
the graph *across* nodes — the two levels the reference collapses into
one.  (The partitioned local fixpoint currently runs the pointer plane;
the device planes plug in behind the same dmark interface.)
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Dict, List, Optional, Set, Tuple

from ...parallel.partition import PartitionMap, ReductionTree, cell_key
from ...runtime import wire
from ...utils import events
from .collector import Bookkeeper, DeltaMsg, _phase
from .delta import DeltaGraph
from .shadow import ShadowGraph, dispatch_kills

if TYPE_CHECKING:  # pragma: no cover
    from .engine import CRGC


# ------------------------------------------------------------------- #
# Protocol messages.  One shape for both fabrics: over a NodeFabric
# they cross as the dedicated version-tolerant frames (runtime/wire.py
# encode_dmark & co., decoded back into these classes by the frame
# handler); over the in-process Fabric they ride control_send as plain
# picklable objects.  Actor coordinates are always (address, uid) key
# tuples — never cells — so a frame round-trip cannot drag object
# graphs across the wire.
# ------------------------------------------------------------------- #


class DWave:
    __slots__ = ("wave", "fence", "origin", "round_id")

    def __init__(self, wave: int, fence: int, origin: str, round_id: int = 0):
        self.wave, self.fence, self.origin = wave, fence, origin
        self.round_id = round_id


class DMark:
    __slots__ = ("wave", "fence", "origin", "keys", "start", "round_id")

    def __init__(
        self, wave: int, fence: int, origin: str, keys: list,
        start: int = 0, round_id: int = 0,
    ):
        self.wave, self.fence, self.origin, self.keys = wave, fence, origin, keys
        self.start = start
        self.round_id = round_id


class DMack:
    __slots__ = ("wave", "origin", "count", "fence", "round_id", "report")

    def __init__(
        self, wave: int, origin: str, count: int, fence: int = 0,
        round_id: int = 0, report=None,
    ):
        self.wave, self.origin, self.count = wave, origin, count
        self.fence = fence
        self.round_id = round_id
        self.report = report


class DProbe:
    __slots__ = ("wave", "round_id", "origin", "fence")

    def __init__(self, wave: int, round_id: int, origin: str, fence: int = 0):
        self.wave, self.round_id, self.origin = wave, round_id, origin
        self.fence = fence


class DStat:
    __slots__ = ("wave", "round_id", "origin", "stats", "fence")

    def __init__(
        self, wave: int, round_id: int, origin: str, stats: dict,
        fence: int = 0,
    ):
        self.wave, self.round_id, self.origin, self.stats = (
            wave, round_id, origin, stats,
        )
        self.fence = fence


class DFin:
    __slots__ = ("wave", "fence", "origin")

    def __init__(self, wave: int, fence: int, origin: str):
        self.wave, self.fence, self.origin = wave, fence, origin


class DGate:
    __slots__ = ("wave", "fence", "origin", "pairs")

    def __init__(self, wave: int, fence: int, origin: str, pairs: list):
        self.wave, self.fence, self.origin, self.pairs = (
            wave, fence, origin, pairs,
        )


class DGack:
    __slots__ = ("wave", "origin", "count", "fence")

    def __init__(self, wave: int, origin: str, count: int, fence: int = 0):
        self.wave, self.origin, self.count = wave, origin, count
        self.fence = fence


class DDirty:
    __slots__ = ("origin",)

    def __init__(self, origin: str):
        self.origin = origin


class DJournal:
    """A retained per-partition delta journal re-sent to that
    partition's new owner after a membership change (the absorb path).
    Crosses control_send like DeltaMsg; the graph's own wire format
    applies in serialize mode."""

    __slots__ = ("fence", "partition", "graph", "_wire_buf")

    def __init__(self, fence: int, partition: int, graph: DeltaGraph):
        self.fence = fence
        self.partition = partition
        self.graph = graph
        self._wire_buf: Optional[bytes] = None

    def reencode(self, fabric, dst_system) -> "DJournal":
        if self._wire_buf is None:
            self._wire_buf = self.graph.serialize(wire.encode_cell)
        graph = DeltaGraph.deserialize(
            self._wire_buf,
            dst_system.engine.crgc_context,
            wire.make_decode_cell(fabric),
        )
        return DJournal(self.fence, self.partition, graph)


# ------------------------------------------------------------------- #
# The partitioned shadow graph
# ------------------------------------------------------------------- #


class PartitionedShadowGraph(ShadowGraph):
    """A ShadowGraph that is authoritative only for the slice the
    partition map assigns to this node.  Shadows for non-owned actors
    exist only as *mirrors* — edge endpoints and supervisor pointers of
    owned actors — whose authoritative state (flags, balances, edges)
    lives at their owner and never mutates here: marks reaching a
    mirror relay out as dmarks instead of propagating locally.

    ``fold_touched`` records which keys the fold paths wrote
    content-bearing state for since the last audit — the runtime twin
    of lint rule UL014 ("slot mutation outside the owning partition's
    fold path goes through the dmark/delta route"), asserted per sweep
    and by the chaos tests."""

    def __init__(self, context, local_address: Optional[str]):
        super().__init__(context, local_address)
        self.partition_map: Optional[PartitionMap] = None
        #: (address, uid) -> cell for every shadow in the graph; dmark
        #: seeds resolve through it without materializing proxies for
        #: actors this node has never heard of.
        self.key_index: Dict[Tuple[str, int], Any] = {}
        #: keys whose authoritative state a fold wrote since the last
        #: locality audit
        self.fold_touched: Set[Tuple[str, int]] = set()
        #: last audited boundary-edge count (telemetry gauge)
        self.boundary_edges = 0
        #: mirror-decay clock (ticks once per completed wave / idle
        #: wake) and the decayed mirrors parked outside the traversal
        #: working set: cell -> Shadow.  A decayed mirror's OBJECT stays
        #: alive inside its referencing owners' ``outgoing`` dicts (so
        #: edge identity is preserved and later +/-1 folds cancel), but
        #: it leaves ``from_set``/``key_index`` — the per-wave iteration
        #: and population surface — until ownership changes or its last
        #: referencing edge releases.
        self.decay_tick = 0
        self.evicted: Dict[Any, Any] = {}
        self.mirrors_evicted_total = 0

    # -- partition plumbing ---------------------------------------- #

    def set_partition_map(self, pmap: PartitionMap) -> None:
        self.partition_map = pmap
        # Ownership moved: stale locality records would false-positive
        # against the new map, and a decayed mirror may now be OWNED —
        # its authoritative slot must be back in the working set before
        # the absorb path resets/re-folds the gained slices.
        self.fold_touched.clear()
        self._revive_evicted()

    def _revive_evicted(self) -> None:
        """Re-admit every decayed mirror to the working set (called at
        each partition remap: a gained partition's shadows must be
        visible to ``reset_partition`` and the re-fold; still-foreign
        ones simply decay again)."""
        if not self.evicted:
            return
        tick = self.decay_tick
        for cell, shadow in self.evicted.items():
            shadow.touch_tick = tick
            self.from_set.append(shadow)
            self.key_index[cell_key(cell)] = cell
        self.evicted = {}

    def decay_mirrors(self, max_age: int) -> int:
        """Advance the decay clock and move foreign-owned mirrors that
        no fold has mentioned for ``max_age`` ticks out of the working
        set.  Relay correctness is untouched: the fixpoint reaches a
        mirror through its referencing owner's ``outgoing`` dict and
        relays by key — residency in ``from_set``/``key_index`` is pure
        iteration/population surface (the hub-node full-replica
        convergence this decays away).

        The O(population) scan runs only every ``max_age`` ticks — a
        shadow cannot expire sooner than one full window after its
        last touch — so idle collector wakes pay amortized
        O(pop / max_age), never a full sweep per 10ms tick."""
        pmap = self.partition_map
        if max_age <= 0 or pmap is None:
            return 0
        self.decay_tick += 1
        if self.decay_tick % max_age:
            return 0
        floor = self.decay_tick - max_age
        keep: List[Any] = []
        evicted = self.evicted
        n = 0
        for shadow in self.from_set:
            if (
                shadow.touch_tick <= floor
                and not self.owns_shadow(shadow)
            ):
                cell = shadow.self_cell
                evicted[cell] = shadow
                self.key_index.pop(cell_key(cell), None)
                n += 1
                continue
            keep.append(shadow)
        if n:
            self.from_set = keep
            self.mirrors_evicted_total += n
            events.recorder.commit(
                events.DIST_MIRROR_EVICT,
                count=n,
                resident=len(keep),
                node=self.local_address,
            )
        return n

    def owns_key(self, key: Tuple[str, int]) -> bool:
        pmap = self.partition_map
        return pmap is not None and pmap.owns(key)

    def shadow_partition(self, shadow) -> Optional[int]:
        """The shadow's partition id, memoized on the shadow itself —
        key->partition is pure, and the ownership checks below run
        O(V+E) times per wave."""
        pmap = self.partition_map
        if pmap is None:
            return None
        p = shadow.partition
        if p is None:
            p = shadow.partition = pmap.partition_of(
                cell_key(shadow.self_cell)
            )
        return p

    def owns_shadow(self, shadow) -> bool:
        pmap = self.partition_map
        if pmap is None:
            return False
        return pmap.owns_partition(self.shadow_partition(shadow))

    def make_shadow(self, cell):
        shadow = super().make_shadow(cell)
        shadow.touch_tick = self.decay_tick
        self.key_index[cell_key(cell)] = cell
        return shadow

    def drop_shadow(self, cell) -> None:
        self.shadow_map.pop(cell, None)
        self.evicted.pop(cell, None)
        self.key_index.pop(cell_key(cell), None)

    def shadow_for_key(self, key: Tuple[str, int]):
        cell = self.key_index.get(key)
        if cell is None:
            return None
        return self.shadow_map.get(cell)

    # -- folds (locality-audited) ----------------------------------- #

    def merge_delta(self, delta) -> None:
        # Record which keys this delta writes authoritative state for
        # BEFORE folding: a content-bearing delta shadow (flags,
        # balance, supervisor, or edges) mutates its actor's slot; a
        # bare mention only ensures existence.
        # One pass over the decoder does double duty: record the
        # content-bearing keys for the locality audit, and refresh the
        # mirror-decay clock for every RESIDENT shadow the delta
        # mentions ("an owned edge touched it").  A decayed mirror is
        # deliberately NOT revived — ``get_shadow`` resolves it through
        # ``shadow_map``, so edge identity (and +/-1 fold cancellation)
        # is preserved without re-growing the working set; shadows the
        # fold CREATES get their tick in ``make_shadow``.
        decoder = delta.decoder()
        touched = self.fold_touched
        tick = self.decay_tick
        smap = self.shadow_map
        evicted = self.evicted
        for i, ds in enumerate(delta.shadows):
            cell = decoder[i]
            if cell is None:
                continue
            if ds.interned or ds.recv_count or ds.supervisor >= 0 or ds.outgoing:
                touched.add(cell_key(cell))
            if cell not in evicted:
                shadow = smap.get(cell)
                if shadow is not None:
                    shadow.touch_tick = tick
        super().merge_delta(delta)

    def merge_undo_log(self, log) -> None:
        """Partition-restricted undo fold: every node receives the same
        quorum-complete log (ingress entries are broadcast), and each
        owner applies exactly the slice it owns — halts for owned
        actors hosted on the dead node, admitted-count reverts for
        owned recipients.  Non-owned adjustments are applied by THEIR
        owners from their own copy of the log."""
        from .shadow import _update_outgoing

        touched = self.fold_touched
        for shadow in self.from_set:
            if not self.owns_shadow(shadow):
                continue
            wrote = False
            if shadow.location == log.node_address:
                shadow.is_halted = True
                wrote = True
            field = log.admitted.get(shadow.self_cell)
            if field is not None:
                shadow.recv_count += field.message_count
                for target_cell, count in field.created_refs.items():
                    _update_outgoing(
                        shadow.outgoing, self.get_shadow(target_cell), count
                    )
                wrote = True
            if wrote:
                touched.add(cell_key(shadow.self_cell))

    def reset_partition(self, partitions: Set[int]) -> int:
        """In-place reset of the owned slice for ``partitions`` ahead of
        a journal re-fold (the absorb path).  Shadow OBJECTS are kept —
        edges from other partitions' shadows reference them by identity,
        and popping would strand those edges on orphans — only their
        authoritative state is cleared."""
        pmap = self.partition_map
        if pmap is None:
            return 0
        from .shadow import clear_authoritative_state

        n = 0
        for shadow in self.from_set:
            if self.shadow_partition(shadow) in partitions:
                clear_authoritative_state(shadow)
                n += 1
        return n

    # -- audits ------------------------------------------------------ #

    def audit_fold_locality(self) -> List[Tuple[str, int]]:
        """Keys whose authoritative state was folded here although the
        current map assigns them elsewhere.  Empty on a healthy node;
        nonempty means a fold bypassed the delta route (the UL014
        class).  Clears the audit window."""
        pmap = self.partition_map
        bad = (
            [k for k in self.fold_touched if not pmap.owns(k)]
            if pmap is not None
            else []
        )
        self.fold_touched.clear()
        return bad

    def boundary_edge_count(self) -> int:
        """Edges whose destination's slice lives on another node — the
        cross-node surface each wave's dmarks cover (telemetry:
        uigc_dist_boundary_edges)."""
        pmap = self.partition_map
        if pmap is None:
            return 0
        n = 0
        for shadow in self.from_set:
            if not self.owns_shadow(shadow):
                continue
            for target, count in shadow.outgoing.items():
                if count > 0 and not self.owns_shadow(target):
                    n += 1
            sup = shadow.supervisor
            if sup is not None and not self.owns_shadow(sup):
                n += 1
        self.boundary_edges = n
        return n

    def owned_population(self) -> int:
        return sum(1 for s in self.from_set if self.owns_shadow(s))


# ------------------------------------------------------------------- #
# Wave state
# ------------------------------------------------------------------- #


class _WaveState:
    __slots__ = (
        "wave", "fence", "marked", "queue", "seeded",
        "out_marks", "out_sets", "sent_upto", "acked",
        "recv_upto", "recv_ahead",
        "changed", "reported_round", "probe_round_seen", "child_stats",
        "fin", "idle",
        # root only
        "probe_round", "round_done", "quiet_sig", "rounds_run",
    )

    def __init__(self, wave: int, fence: int):
        self.wave = wave
        self.fence = fence
        self.marked: Set[Any] = set()          # Shadow objects (owned)
        self.queue: List[Any] = []             # pending propagation
        self.seeded = False
        self.out_marks: Dict[str, List] = {}   # peer -> ordered key list
        self.out_sets: Dict[str, Set] = {}     # peer -> key set (dedup)
        #: peer -> flush watermark (keys [0:sent_upto] already flushed
        #: this wave; the suffix protocol sends only past it)
        self.sent_upto: Dict[str, int] = {}
        #: peer -> acked contiguous-coverage watermark
        self.acked: Dict[str, int] = {}
        #: src -> contiguous received-position watermark
        self.recv_upto: Dict[str, int] = {}
        #: src -> out-of-order positions past the watermark
        self.recv_ahead: Dict[str, Set[int]] = {}
        self.changed = False
        self.reported_round = 0
        self.probe_round_seen = 0
        self.child_stats: Dict[int, Dict[str, dict]] = {}
        self.fin = False
        self.idle = 0
        self.probe_round = 0
        self.round_done: Dict[int, bool] = {}
        #: the (sent, recv) signature of the last judged all-settled
        #: sent==recv round; an identical signature on the NEXT judged
        #: round proves the global fixpoint (the two-consecutive-quiet
        #: criterion — Mattern's four-counter argument over idempotent
        #: cumulative mark sets)
        self.quiet_sig: Optional[tuple] = None
        self.rounds_run = 0

    def sent_total(self) -> int:
        return sum(len(lst) for lst in self.out_marks.values())

    def recv_total(self) -> int:
        srcs = set(self.recv_upto) | set(self.recv_ahead)
        return sum(
            self.recv_upto.get(s, 0) + len(self.recv_ahead.get(s, ()))
            for s in srcs
        )

    def settled(self) -> bool:
        if self.queue:
            return False
        for peer, lst in self.out_marks.items():
            if self.acked.get(peer, 0) < len(lst):
                return False
        return True


# ------------------------------------------------------------------- #
# The distributed Bookkeeper
# ------------------------------------------------------------------- #


class DistributedBookkeeper(Bookkeeper):
    """Collector loop for the partitioned mode.  Same cell, same timers,
    same membership plumbing as the replicated Bookkeeper — different
    fold routing and a wave protocol in place of the local trace."""

    def __init__(self, engine: "CRGC"):
        super().__init__(engine)
        config = engine.system.config
        n = config.get_int("uigc.crgc.dist-partitions")
        if n <= 0:
            n = config.get_int("uigc.cluster.num-shards")
        self.num_partitions = n
        self.fence = 0
        #: a higher era was adopted from a peer frame since the last
        #: remap (suppresses the remap's own +1 for that transition)
        self._fence_adopted = False
        self.pmap: Optional[PartitionMap] = None
        self.tree: Optional[ReductionTree] = None
        self.wave = 0
        self.ws: Optional[_WaveState] = None
        self._last_wave_done = 0
        self._last_marked: Set[Tuple[str, int]] = set()
        #: partition -> cumulative DeltaGraph of the facts THIS node
        #: generated for that partition (the absorb journal)
        self._retained: Dict[int, DeltaGraph] = {}
        #: partition -> size at its last compaction (the doubling
        #: floor that amortizes _compact_retained)
        self._retained_floor: Dict[int, int] = {}
        self._pending_deltas: List[DeltaGraph] = []
        self._pending_journals: List[DJournal] = []
        self._pending_undo: List[Any] = []
        self._dirty_hint = False
        #: re-entrancy latch for sweep -> next-wave chaining
        self._chain_guard = False
        #: foreign-owned mirrors leave the traversal working set after
        #: this many decay ticks without a fold touching them (0 = off)
        self.mirror_decay = config.get_int("uigc.crgc.mirror-decay-waves")
        #: remote-supervisor kill gates from the last sweep, re-derived
        #: per wave; unacked gates keep the graph dirty so the next
        #: wave retries (a lost frame delays, never leaks a kill
        #: decision)
        self._gates_wave = 0
        self._gates_out: Dict[str, List] = {}
        self._gates_acked: Dict[str, int] = {}
        #: (origin, wave) -> processed gate-pair set (dedup + ack count)
        self._gates_seen: Dict[Tuple[str, int], Set] = {}
        # Per-owner delta builders for the current drain.
        self._builders: Dict[str, DeltaGraph] = {}
        # Stats for the bench / dashboard.
        self.waves_completed = 0
        self.total_dist_garbage = 0
        self.marks_sent = 0
        self.mark_bytes = 0
        self.marks_received = 0
        self.rounds_total = 0

    # -- plumbing ---------------------------------------------------- #

    @property
    def _me(self) -> str:
        return self.engine.system.address

    def _graph(self):
        # Through the sanitizer's mirror when attached: custom methods
        # pass straight through its __getattr__, fold methods are
        # observed — which is exactly the contract the oracle needs.
        return self.shadow_graph

    def bind(self, cell: Any) -> None:
        super().bind(cell)
        fabric = self.engine.system.fabric
        reg = getattr(fabric, "register_frame_handler", None)
        if reg is not None:
            for kind in wire.DIST_FRAME_KINDS:
                reg(kind, self._on_dist_frame)

    def _on_dist_frame(self, from_address: str, frame: tuple) -> None:
        """Transport-thread entry: decode (tolerantly) and hand the
        message to the collector cell — all protocol state mutates on
        the one thread that owns the graph."""
        kind = frame[0]
        msg: Any = None
        if kind == "dwave":
            d = wire.decode_dwave(frame)
            msg = DWave(*d) if d else None
        elif kind == "dmark":
            d = wire.decode_dmark(frame)
            msg = DMark(*d) if d else None
        elif kind == "dmack":
            d = wire.decode_dmack(frame)
            msg = DMack(*d) if d else None
        elif kind == "dprobe":
            d = wire.decode_dprobe(frame)
            msg = DProbe(*d) if d else None
        elif kind == "dstat":
            d = wire.decode_dstat(frame)
            msg = DStat(*d) if d else None
        elif kind == "dfin":
            d = wire.decode_dfin(frame)
            msg = DFin(*d) if d else None
        elif kind == "dgate":
            d = wire.decode_dgate(frame)
            msg = DGate(*d) if d else None
        elif kind == "dgack":
            d = wire.decode_dgack(frame)
            msg = DGack(*d) if d else None
        elif kind == "ddirty":
            d = wire.decode_ddirty(frame)
            msg = DDirty(d) if d else None
        elif kind == "djnl":
            d = wire.decode_djournal(frame)
            if d is not None:
                try:
                    graph = DeltaGraph.deserialize(
                        d[2],
                        self.engine.crgc_context,
                        wire.make_decode_cell(self.engine.system.fabric),
                    )
                except Exception:
                    graph = None  # malformed journal: drop (leak-safe)
                if graph is not None:
                    msg = DJournal(d[0], d[1], graph)
        if msg is not None:
            self.cell.tell(msg)

    def _send_dist(self, peer: str, frame: tuple, msg: Any) -> None:
        """One protocol send: the dedicated frame on a NodeFabric (so
        FaultPlan can target the kind and mixed versions stay
        tolerant), the message object over the in-process fabric."""
        if peer == self._me:
            return
        fabric = self.engine.system.fabric
        send = getattr(fabric, "send_frame", None)
        if send is not None:
            send(peer, frame)
            return
        gc = self.remote_gcs.get(peer)
        if gc is not None:
            fabric.control_send(self.engine.system, gc, msg)

    def _resolve_key(self, key: Tuple[str, int]):
        """Key -> cell, for kill dispatch: the graph's index first (no
        allocation), the fabric's token resolver second."""
        cell = self._graph().key_index.get(key)
        if cell is not None:
            return cell
        fabric = self.engine.system.fabric
        hook = getattr(fabric, "resolve_cell_token", None)
        if hook is not None:
            try:
                return hook(key[0], key[1])
            except Exception:
                return None
        system = fabric.systems.get(key[0])
        if system is None:
            return None
        return system.resolve_cell(key[1])

    # -- membership -------------------------------------------------- #

    def add_member(self, address: str) -> None:
        before = self.started
        super().add_member(address)
        if self.multi_node and address in self.remote_gcs:
            self._remap_partitions()
        if not before and self.started:
            self._graph_dirty = True

    def remove_member(self, address: str) -> None:
        super().remove_member(address)
        if self.multi_node:
            self._remap_partitions()

    def _cluster_fence(self) -> int:
        """Reuse the PR 13 arbiter's fence when cluster sharding is
        attached, so the collector's partition era and the shard
        plane's quarantine era can never diverge."""
        cluster = getattr(self.engine.system, "cluster", None)
        arb = getattr(cluster, "arbiter", None)
        return getattr(arb, "fence", 0) if arb is not None else 0

    def _reset_wave_plane(self) -> None:
        """A fence change opens a new wave ERA: wave ids restart at 1
        (the root mints them), completed-wave watermarks and gate state
        reset, and the in-flight wave aborts.  Every live node runs the
        identical reset at the same membership transition, so the
        numbering stays agreed; the wave-keyed frames carry the fence,
        so a straggler from the old era can never alias the new one."""
        self.wave = 0
        self._last_wave_done = 0
        self._last_marked = set()
        self.ws = None
        self._gates_wave = 0
        self._gates_out = {}
        self._gates_acked = {}
        self._gates_seen = {}

    def _adopt_fence(self, fence: int) -> bool:
        """A frame from a higher partition era than our local
        transition count reached — we are the node that was dead, or we
        joined late and missed transitions.  Adopt the era (same member
        view, re-stamped) so fences converge to the cluster max with
        zero coordination frames; our own lower-era frames were dropped
        by the peers and re-send under the adopted era."""
        if fence <= self.fence:
            return False
        self.fence = fence
        # The adopted era was minted by a peer's remap — usually for a
        # membership transition WE have not processed yet.  Our own
        # remap for that transition must not bump past it, or every
        # membership change costs the cluster two era resets instead
        # of one (see _remap_partitions).
        self._fence_adopted = True
        if self.pmap is not None:
            self.pmap = PartitionMap(
                self.pmap.members, self.num_partitions, fence, self._me,
                cache=self.pmap._pcache,
            )
            self._graph().set_partition_map(self.pmap)
            if self.tree is None:
                self.tree = ReductionTree(self.pmap.members)
        self._reset_wave_plane()
        self._graph_dirty = True
        self._fold_ready_journals()
        return True

    def _remap_partitions(self) -> None:
        members = sorted([self._me] + list(self.remote_gcs))
        old = self.pmap
        if old is not None and old.members == members:
            return
        if old is not None and not self._fence_adopted:
            self.fence = max(self.fence + 1, self._cluster_fence())
        else:
            # First map, or an adopted era already covers this
            # transition (the peer that minted it had processed it).
            self.fence = max(self.fence, self._cluster_fence())
        self._fence_adopted = False
        self.pmap = PartitionMap(
            members, self.num_partitions, self.fence, self._me,
            cache=old._pcache if old is not None else None,
        )
        self.tree = ReductionTree(members)
        g = self._graph()
        g.set_partition_map(self.pmap)
        # New era: abort the in-flight wave (its marks were computed
        # against the old ownership and member set) and restart the
        # wave numbering — see _reset_wave_plane.
        self._reset_wave_plane()
        self._graph_dirty = True
        if old is None:
            return
        moved = self.pmap.moved_partitions(old)
        if not moved:
            return
        gained = [p for p in moved if self.pmap.owner(p) == self._me]
        if gained:
            # Absorb: reset the gained slices in place, then re-fold
            # this node's own journal; the surviving peers re-send
            # theirs below (each under the bumped fence).
            g.reset_partition(set(gained))
            for p in gained:
                journal = self._retained.get(p)
                if journal is not None and journal.non_empty():
                    g.merge_delta(journal)
                    events.recorder.commit(
                        events.DIST_REFOLD,
                        partition=p,
                        shadows=journal.size,
                        node=self._me,
                        fence=self.fence,
                    )
        for p in moved:
            owner = self.pmap.owner(p)
            if owner is None or owner == self._me:
                continue
            journal = self._retained.get(p)
            if journal is not None and journal.non_empty():
                fabric = self.engine.system.fabric
                send = getattr(fabric, "send_frame", None)
                if send is not None:
                    send(
                        owner,
                        wire.encode_djournal(
                            self.fence, p, journal.serialize(wire.encode_cell)
                        ),
                    )
                else:
                    gc = self.remote_gcs.get(owner)
                    if gc is not None:
                        fabric.control_send(
                            self.engine.system,
                            gc,
                            DJournal(self.fence, p, journal),
                        )
        # Fold journals that arrived ahead of our own fence bump.
        self._fold_ready_journals()

    # -- message dispatch -------------------------------------------- #

    def on_message(self, msg: Any) -> Any:
        if isinstance(msg, DWave):
            self._on_dwave(msg)
        elif isinstance(msg, DMark):
            self._on_dmark(msg)
        elif isinstance(msg, DMack):
            self._on_dmack(msg)
        elif isinstance(msg, DProbe):
            self._on_dprobe(msg)
        elif isinstance(msg, DStat):
            self._on_dstat(msg)
        elif isinstance(msg, DFin):
            self._on_dfin(msg)
        elif isinstance(msg, DGate):
            self._on_dgate(msg)
        elif isinstance(msg, DGack):
            self._on_dgack(msg)
        elif isinstance(msg, DDirty):
            self._dirty_hint = True
            # Event-driven wave start: the root opens the wave the
            # moment the hint lands instead of on its next timer wake.
            self._maybe_begin_wave()
        elif isinstance(msg, DJournal):
            self._on_djournal(msg)
        else:
            return super().on_message(msg)
        return None

    # -- fold routing ------------------------------------------------ #

    def _scrub_strayed_keys(self) -> None:
        """A delta routed under an older partition map can land after a
        remap: its content keys are no longer owned here, which is a
        sender-side race (the facts re-ship to the new owner via the
        retained journal), not a fold-locality bug.  Drop those keys
        from the audit window so crgc.dist_locality_violation keeps its
        'always a bug' meaning — every non-delta fold path (and any
        direct merge_delta outside this router) keeps the full audit."""
        g = self._graph()
        pmap = self.pmap
        if pmap is None:
            return
        touched = g.fold_touched
        for key in [k for k in touched if not pmap.owns(k)]:
            touched.discard(key)

    def handle_delta(self, graph: DeltaGraph) -> None:
        if graph.address not in self.remote_gcs:
            return
        # The undo accounting must see every peer delta immediately
        # (it reverts the SENDER's unadmitted claims at its death);
        # the graph fold defers past an active wave so each wave
        # traces one consistent snapshot.
        self.undo_logs[graph.address].merge_delta_graph(graph)
        if self.ws is not None or self.pmap is None:
            self._pending_deltas.append(graph)
        else:
            with events.recorder.timed(events.MERGING_DELTA_GRAPHS):
                self._graph().merge_delta(graph)
            self._scrub_strayed_keys()
            self._graph_dirty = True

    def _on_djournal(self, msg: DJournal) -> None:
        """Deliberately does NOT adopt a higher fence here: a journal
        can outrun our own MemberRemoved, and adopting would make
        _fold_ready_journals judge its ownership against the STALE
        member view (and drop it).  Pending until our remap catches up
        keeps the fold correct in both orders."""
        if msg.fence < self.fence:
            return  # a stale era's absorb — superseded
        self._pending_journals.append(msg)
        self._fold_ready_journals()

    def _fold_ready_journals(self) -> None:
        if self.ws is not None:
            return
        keep: List[DJournal] = []
        for j in self._pending_journals:
            if j.fence > self.fence:
                keep.append(j)  # our membership view hasn't caught up
            elif j.fence == self.fence and self.pmap is not None:
                if self.pmap.owner(j.partition) == self._me:
                    with events.recorder.timed(events.MERGING_DELTA_GRAPHS):
                        self._graph().merge_delta(j.graph)
                    self._scrub_strayed_keys()
                    events.recorder.commit(
                        events.DIST_REFOLD,
                        partition=j.partition,
                        shadows=j.graph.size,
                        node=self._me,
                        fence=self.fence,
                    )
                    self._graph_dirty = True
            # stale fence or not-owned: drop (leak-safe; the sender
            # re-ships under the next fence if ownership says so)
        self._pending_journals = keep

    def _builder(self, owner: str) -> DeltaGraph:
        b = self._builders.get(owner)
        if b is None:
            b = DeltaGraph(self._me, self.engine.crgc_context)
            self._builders[owner] = b
        return b

    def _retained_for(self, partition: int) -> DeltaGraph:
        j = self._retained.get(partition)
        if j is None:
            j = DeltaGraph(self._me, self.engine.crgc_context)
            self._retained[partition] = j
        return j

    def _sinks(self, cell) -> Tuple[DeltaGraph, DeltaGraph]:
        """(owner builder, retained journal) for one affected actor."""
        key = cell_key(cell)
        p = self.pmap.partition_of(key)
        owner = self.pmap.owner(p) or self._me
        return self._builder(owner), self._retained_for(p)

    def _route_entry(self, entry: Any) -> None:
        """Split one mutator snapshot's effects per affected actor's
        owner — the partitioned replacement for folding the whole entry
        into a local replica."""
        from . import refob as refob_info

        self_cell = entry.self_ref.target
        for sink in self._sinks(self_cell):
            sink.fold_self(
                self_cell, entry.recv_count, entry.is_busy, entry.is_root
            )
        field_size = self.engine.crgc_context.entry_field_size
        for i in range(field_size):
            owner_ref = entry.created_owners[i]
            if owner_ref is None:
                break
            owner_cell = owner_ref.target
            target_cell = entry.created_targets[i].target
            for sink in self._sinks(owner_cell):
                sink.fold_created(owner_cell, target_cell)
            for sink in self._sinks(target_cell):
                sink.touch(target_cell)
        for i in range(field_size):
            child = entry.spawned_actors[i]
            if child is None:
                break
            child_cell = child.target
            for sink in self._sinks(child_cell):
                sink.fold_spawned(child_cell, self_cell)
        for i in range(field_size):
            target = entry.updated_refs[i]
            if target is None:
                break
            target_cell = target.target
            info = entry.updated_infos[i]
            send_count = refob_info.count(info)
            if send_count > 0:
                for sink in self._sinks(target_cell):
                    sink.fold_sends(target_cell, send_count)
            if not refob_info.is_active(info):
                for sink in self._sinks(self_cell):
                    sink.fold_deactivate(self_cell, target_cell)

    def _flush_builders(self) -> None:
        fabric = self.engine.system.fabric
        for owner, delta in self._builders.items():
            if not delta.non_empty():
                continue
            if owner == self._me:
                if self.ws is not None:
                    self._pending_deltas.append(delta)
                else:
                    with events.recorder.timed(events.MERGING_DELTA_GRAPHS):
                        self._graph().merge_delta(delta)
                    self._scrub_strayed_keys()
                    self._graph_dirty = True
                continue
            gc = self.remote_gcs.get(owner)
            if gc is not None:
                fabric.control_send(
                    self.engine.system, gc, DeltaMsg(self.delta_graph_id, delta)
                )
                self.delta_graph_id += 1
        self._builders = {}

    def _fold_pending(self) -> None:
        """Fold everything a wave deferred (peer deltas, undo logs,
        absorb journals) — only between waves, so each wave's trace is
        a consistent snapshot."""
        if self.ws is not None:
            return
        if self._pending_deltas:
            g = self._graph()
            with events.recorder.timed(events.MERGING_DELTA_GRAPHS):
                for delta in self._pending_deltas:
                    g.merge_delta(delta)
            self._pending_deltas = []
            self._scrub_strayed_keys()
            self._graph_dirty = True
        if self._pending_undo:
            g = self._graph()
            for log in self._pending_undo:
                g.merge_undo_log(log)
            self._pending_undo = []
            self._graph_dirty = True
        self._fold_ready_journals()

    def _maybe_fold_undo_log(self, addr: str) -> None:
        """Same exactly-once quorum as the base collector, but the fold
        defers past an active wave and never runs its own trace — the
        wave machinery re-derives verdicts from the folded state."""
        if addr in self.undone_gcs:
            return
        log = self.undo_logs.get(addr)
        if log is None:
            return
        my_addr = self._me
        if my_addr in log.finalized_by and all(
            peer in log.finalized_by for peer in self.remote_gcs
        ):
            self.undone_gcs.add(addr)
            events.recorder.commit(
                events.UNDO_FOLD, address=addr, node=my_addr, **log.summary()
            )
            self._pending_undo.append(log)
            self._graph_dirty = True
            if self.ws is None:
                self._fold_pending()

    # -- the collector wake ------------------------------------------ #

    def _collect_inner(self, wake: Any) -> tuple:
        engine = self.engine
        queue = engine.queue
        pool = engine.entry_pool
        count = 0
        with events.recorder.timed(events.PROCESSING_ENTRIES) as ev:
            with _phase(wake, "ingest"):
                batch = []
                while True:
                    try:
                        entry = queue.popleft()
                    except IndexError:
                        break
                    count += 1
                    batch.append(entry)
            with _phase(wake, "fold"):
                if batch and self.pmap is not None:
                    for entry in batch:
                        self._route_entry(entry)
                    for entry in batch:
                        entry.clean()
                        pool.append(entry)
                elif batch:
                    # Membership not yet complete: push back and retry
                    # next wake (GC is gated on full membership anyway).
                    for entry in reversed(batch):
                        queue.appendleft(entry)
                    count = 0
            with _phase(wake, "broadcast"):
                self._flush_builders()
            ev.fields["num_entries"] = count
        self.total_entries += count
        if count:
            self._graph_dirty = True
        with _phase(wake, "trace"):
            n_garbage = self._wave_step()
        return count, n_garbage

    # -- wave machinery ---------------------------------------------- #

    def _is_root(self) -> bool:
        return self.tree is not None and self.tree.root == self._me

    def _gates_pending(self) -> bool:
        for peer, lst in self._gates_out.items():
            if self._gates_acked.get(peer, 0) < len(lst):
                return True
        return False

    def _wave_step(self) -> int:
        """The per-wake driver.  Since the pipelined rework this is the
        RETRANSMIT / healing plane: marks, acks, probes and reports all
        fire event-driven as frames arrive (:meth:`_pump`), so a
        healthy wave converges at message latency; the wake re-drives
        whatever a dropped frame stalled."""
        if self.pmap is None or not self.started:
            return 0
        n_garbage = 0
        if self.ws is None:
            self._fold_pending()
            self._resend_gates()
            self._maybe_begin_wave()
            self._graph().decay_mirrors(self.mirror_decay)
        ws = self.ws
        if ws is not None:
            self._fixpoint(ws)
            self._send_dmarks(ws, retransmit=True)
            if self._is_root():
                # Keep late joiners / dropped dwave frames in the wave
                # (the round stamp rides along — dprobe's fallback).
                for peer in self.remote_gcs:
                    self._send_dist(
                        peer,
                        wire.encode_dwave(
                            ws.wave, ws.fence, self._me, ws.probe_round
                        ),
                        DWave(ws.wave, ws.fence, self._me, ws.probe_round),
                    )
                self._root_termination(ws)
            self._flush_stat_report(ws)
            if not ws.fin and not self._is_root():
                # Fin-loss healing: a settled, reported, change-free
                # node that hears nothing for a few wakes re-reports
                # its aggregate unsolicited; an ancestor that already
                # completed this wave re-serves the dfin (see
                # _on_dstat), so a dropped dfin can only delay a sweep.
                if ws.settled() and ws.reported_round > 0 and not ws.queue:
                    ws.idle += 1
                    if ws.idle >= 3:
                        ws.idle = 0
                        ws.reported_round = ws.probe_round_seen - 1
                        self._flush_stat_report(ws)
                else:
                    ws.idle = 0
            if ws.fin:
                n_garbage = self._sweep(ws)
        return n_garbage

    def _maybe_begin_wave(self) -> None:
        """Start (root) or solicit (non-root) a wave when dirty work is
        waiting and none is in flight."""
        if self.ws is not None or self.pmap is None or not self.started:
            return
        if self._is_root():
            if self._graph_dirty or self._dirty_hint or self._gates_pending():
                self._start_wave()
                ws = self.ws
                if ws is not None:
                    self._pump(ws)
        elif self._graph_dirty or self._gates_pending():
            root = self.tree.root
            if root is not None and root != self._me:
                self._send_dist(
                    root, wire.encode_ddirty(self._me), DDirty(self._me)
                )

    def _pump(self, ws: _WaveState) -> None:
        """One event-driven propagation step: drain the local fixpoint,
        flush fresh boundary marks, push the termination machinery.
        Called from every protocol-frame handler, so mark propagation
        crosses the cluster at message latency instead of one hop per
        collector wake — the latency collapse that lets the partitioned
        trace outrun the replicated fold."""
        self._fixpoint(ws)
        self._send_dmarks(ws)
        self._finish_pump(ws)

    def _start_wave(self) -> None:
        self._fold_pending()
        self.wave += 1
        self._dirty_hint = False
        self._graph_dirty = False
        self.ws = _WaveState(self.wave, self.fence)
        for peer in self.remote_gcs:
            self._send_dist(
                peer,
                wire.encode_dwave(self.wave, self.fence, self._me),
                DWave(self.wave, self.fence, self._me),
            )

    def _enter_wave(self, wave: int, fence: int) -> bool:
        """Adopt a wave the root (or a peer's dmark racing the dwave)
        announced.  A HIGHER fence is adopted first (our membership
        view lags — see _adopt_fence); frames from an older era are
        ignored — the sender re-ships once its view converges."""
        if self.pmap is None:
            # Join race: a peer whose membership completed first can
            # open a wave before our partition map exists.  Refuse the
            # wave (no state to trace against, and the mark handlers
            # consult the map); the sender's wake-driven retransmits
            # re-deliver once our remap lands.
            return False
        if fence > self.fence:
            self._adopt_fence(fence)
        if fence != self.fence:
            return False
        if wave <= self._last_wave_done:
            return False
        ws = self.ws
        if ws is not None:
            if ws.wave == wave:
                return True
            if ws.wave > wave:
                return False
            self.ws = None  # a newer wave supersedes; re-derive
        self._fold_pending()
        self.wave = max(self.wave, wave)
        self._graph_dirty = False
        self.ws = _WaveState(wave, fence)
        return True

    def _owned(self, shadow) -> bool:
        # Through the graph's per-shadow partition memo: this runs
        # O(V+E) times per wave and a blake2b per call dominates the
        # trace otherwise.
        return self._graph().owns_shadow(shadow)

    def _fixpoint(self, ws: _WaveState) -> None:
        """Drain the wave's propagation queue: local push over owned
        slots.  Marks crossing a partition boundary never enter the
        queue — they are propagation-blocked straight into the
        per-owner mark buffer at push time (``_relay_mark``), so each
        drain costs one buffer append per boundary edge and the flush
        is O(owners) frames, not O(pending batches)."""
        g = self._graph()
        if not ws.seeded:
            ws.seeded = True
            marked, queue = ws.marked, ws.queue
            for shadow in g.from_set:
                if (
                    self._owned(shadow)
                    and g.is_pseudo_root(shadow)
                    and shadow not in marked
                ):
                    marked.add(shadow)
                    queue.append(shadow)
        queue = ws.queue
        if not queue:
            return
        marked = ws.marked
        owned = self._owned
        relay = self._relay_mark
        progressed = False
        while queue:
            shadow = queue.pop()
            progressed = True
            if shadow.is_halted:
                continue
            for target, count in shadow.outgoing.items():
                if count > 0 and target not in marked:
                    if owned(target):
                        marked.add(target)
                        queue.append(target)
                    else:
                        relay(ws, target)
            sup = shadow.supervisor
            if sup is not None and sup not in marked:
                if owned(sup):
                    marked.add(sup)
                    queue.append(sup)
                else:
                    relay(ws, sup)
        if progressed:
            ws.changed = True

    def _relay_mark(self, ws: _WaveState, shadow: Any) -> None:
        """A mark reached a mirror: buffer its key for the owner (dedup
        per wave), never propagate through non-authoritative state."""
        self._relay_key(ws, cell_key(shadow.self_cell))

    def _relay_key(self, ws: _WaveState, key: Tuple[str, int]) -> None:
        owner = self.pmap.owner_of(key)
        if owner is None or owner == self._me:
            return
        s = ws.out_sets.setdefault(owner, set())
        if key not in s:
            s.add(key)
            ws.out_marks.setdefault(owner, []).append(key)

    def _keyset_capable(self, peer: str) -> bool:
        """Can ``peer`` decode the binary key-set payload?  NodeFabric
        peers advertise SCHEMA_DIST_KEYS through the schema-codec hello
        caps (PR 9); the in-process fabric is the same build by
        construction.  A legacy peer gets the PR-14 JSON shape."""
        fabric = self.engine.system.fabric
        ids_fn = getattr(fabric, "peer_schema_ids", None)
        if ids_fn is None:
            return True
        from ...runtime import schema as wire_schema

        return wire_schema.SCHEMA_DIST_KEYS in ids_fn(peer)

    def _round_stamp(self, ws: _WaveState) -> int:
        return ws.probe_round if self._is_root() else ws.probe_round_seen

    def _send_dmarks(self, ws: _WaveState, retransmit: bool = False) -> None:
        """Flush boundary marks, one frame per owner.  Schema-capable
        peers get the suffix protocol: each flush carries only the keys
        past the flush watermark, binary-encoded; the per-wake
        ``retransmit`` pass re-covers the span past the peer's ACK
        watermark, so drops, dups and reorders all degrade to a
        retransmit of an idempotent, position-addressed set union.
        Legacy (PR-14) peers get the old full-cumulative JSON frame."""
        for peer, lst in ws.out_marks.items():
            total = len(lst)
            acked = ws.acked.get(peer, 0)
            upto = ws.sent_upto.get(peer, 0)
            if self._keyset_capable(peer):
                start = upto
                if retransmit and acked < upto:
                    start = acked
                if start >= total:
                    continue
                chunk = lst[start:]
                frame = wire.encode_dmark(
                    ws.wave, ws.fence, self._me, chunk,
                    start=start, binary=True,
                    round_id=self._round_stamp(ws),
                )
                msg = DMark(
                    ws.wave, ws.fence, self._me, list(chunk),
                    start, self._round_stamp(ws),
                )
            else:
                if acked >= total:
                    continue
                if not retransmit and upto >= total:
                    continue
                chunk = lst
                frame = wire.encode_dmark(
                    ws.wave, ws.fence, self._me, lst, binary=False
                )
                msg = DMark(ws.wave, ws.fence, self._me, list(lst))
            self._send_dist(peer, frame, msg)
            ws.sent_upto[peer] = total
            self.marks_sent += len(chunk)
            self.mark_bytes += len(frame[4])
            events.recorder.commit(
                events.DIST_MARKS,
                count=len(chunk),
                bytes=len(frame[4]),
                dst=peer,
                node=self._me,
            )

    def _note_round(self, ws: _WaveState, round_id: int) -> None:
        """Epidemic round dissemination: every dwave/dmark/dmack frame
        carries the sender's known termination round, so non-roots
        learn the round from the data plane and explicit dprobe frames
        become the drop-healing fallback."""
        if round_id and not self._is_root() and round_id > ws.probe_round_seen:
            ws.probe_round_seen = round_id

    def _on_dwave(self, msg: DWave) -> None:
        if not self._enter_wave(msg.wave, msg.fence):
            return
        ws = self.ws
        if ws is None or ws.wave != msg.wave:
            return
        self._note_round(ws, msg.round_id)
        self._pump(ws)

    def _on_dmark(self, msg: DMark) -> None:
        if not self._enter_wave(msg.wave, msg.fence):
            return
        ws = self.ws
        if ws is None or ws.wave != msg.wave:
            return
        self._note_round(ws, msg.round_id)
        g = self._graph()
        up = ws.recv_upto.get(msg.origin, 0)
        ahead = ws.recv_ahead.setdefault(msg.origin, set())
        # Seed EVERY key in the frame (idempotent via ws.marked):
        # positions below track coverage of the sender's mark list as
        # SPANS only — the binary codec re-orders keys inside a frame
        # (address-grouped, uid-sorted), so per-position key identity
        # is not stable across differently-bounded retransmits, and
        # skipping "already covered" positions key-by-key could drop a
        # mark whose position was covered by a frame that carried a
        # DIFFERENT key there.  A frame's key set is exactly the
        # sender's list[start:start+n] as a set, so span coverage <=>
        # every one of those keys delivered, in any order.
        for key in msg.keys:
            k = (key[0], int(key[1]))
            if not self.pmap.owns(k):
                # Misrouted mark: the sender's partition map disagrees
                # with ours (the _adopt_fence window re-stamps a stale
                # member view at the adopted fence, so two maps can
                # share a fence with divergent ownership).  Forward by
                # OUR map instead of consuming through a mirror — the
                # relay converges as the views do, and a live actor's
                # mark can never be silently absorbed short of its
                # true owner.
                self._relay_key(ws, k)
                continue
            shadow = g.shadow_for_key(k)
            if shadow is not None and shadow not in ws.marked:
                ws.marked.add(shadow)
                ws.queue.append(shadow)
        new = 0
        for pos in range(msg.start, msg.start + len(msg.keys)):
            if pos < up or pos in ahead:
                continue
            ahead.add(pos)
            new += 1
        while up in ahead:
            ahead.discard(up)
            up += 1
        ws.recv_upto[msg.origin] = up
        if new:
            ws.changed = True
            self.marks_received += new
        # Propagate BEFORE acking: the fixpoint drains synchronously,
        # so the ack's piggybacked report (and the termination stats it
        # reflects) already cover the seeds this frame delivered.
        self._fixpoint(ws)
        self._send_dmarks(ws)
        # Always ack with the contiguous watermark — a duplicate
        # frame's ack heals a lost earlier ack.
        rid, report = self._piggyback_report(ws, msg.origin)
        self._send_dist(
            msg.origin,
            wire.encode_dmack(
                ws.wave, self._me, up, self.fence, rid, report
            ),
            DMack(ws.wave, self._me, up, self.fence, rid, report),
        )
        self._finish_pump(ws)

    def _on_dmack(self, msg: DMack) -> None:
        if msg.fence != self.fence:
            self._adopt_fence(msg.fence)
            return  # old era's ack (or we just reset): nothing to count
        ws = self.ws
        if ws is None or ws.wave != msg.wave:
            return
        self._note_round(ws, msg.round_id)
        prev = ws.acked.get(msg.origin, 0)
        if msg.count > prev:
            ws.acked[msg.origin] = msg.count
        if (
            msg.report is not None
            and msg.round_id > 0
            and self.tree is not None
            and msg.origin in self.tree.children(self._me)
            and not self.tree.children(msg.origin)
        ):
            # A leaf child's termination report rode the ack.
            settled, changed, sent, recv, nodes = msg.report
            ws.child_stats.setdefault(msg.round_id, {})[msg.origin] = {
                "settled": bool(settled),
                "changed": bool(changed),
                "sent": sent,
                "recv": recv,
                "nodes": nodes,
            }
        self._pump(ws)

    def _piggyback_report(self, ws: _WaveState, peer: str):
        """(round stamp, report-or-None) for an outgoing dmack: a LEAF
        whose parent is the ack's destination attaches its settled
        report for the current round, so the common termination path
        needs no explicit dstat frame at all."""
        rid = self._round_stamp(ws)
        if (
            self.tree is None
            or self._is_root()
            or peer != self.tree.parent(self._me)
            or self.tree.children(self._me)
            or rid <= ws.reported_round
            or not ws.settled()
        ):
            return rid, None
        agg = self._own_stats(ws)
        ws.reported_round = rid
        return rid, (
            int(agg["settled"]), int(agg["changed"]),
            agg["sent"], agg["recv"], agg["nodes"],
        )

    # -- termination (Safra over the reduction tree) ----------------- #

    def _finish_pump(self, ws: _WaveState) -> None:
        """Termination tail of one pump: judge/report, and when the
        wave finished, sweep NOW (not on the next timer wake) and chain
        straight into the next wave if dirty work is already waiting —
        the pipelining that removes every wake-interval barrier from
        the wave lifecycle."""
        if self._is_root():
            self._root_termination(ws)
        else:
            self._flush_stat_report(ws)
        if ws.fin and self.ws is ws:
            n_garbage = self._sweep(ws)
            self._after_wake(n_garbage)
            self._chain_after_sweep()

    def _chain_after_sweep(self) -> None:
        # Re-entrancy latch: a chained wave that somehow finishes
        # synchronously (single-member trees) must not recurse through
        # sweep->begin->sweep — the timer wake picks the tail up.
        if self._chain_guard:
            return
        self._chain_guard = True
        try:
            self._maybe_begin_wave()
        finally:
            self._chain_guard = False

    def _own_stats(self, ws: _WaveState) -> dict:
        stats = {
            "settled": ws.settled(),
            "changed": ws.changed,
            "sent": ws.sent_total(),
            "recv": ws.recv_total(),
            "nodes": 1,
        }
        ws.changed = False
        return stats

    @staticmethod
    def _merge_stats(agg: dict, stats: dict) -> None:
        agg["settled"] = agg["settled"] and bool(stats.get("settled"))
        agg["changed"] = agg["changed"] or bool(stats.get("changed"))
        agg["sent"] += int(stats.get("sent", 0))
        agg["recv"] += int(stats.get("recv", 0))
        agg["nodes"] += int(stats.get("nodes", 1))

    def _on_dprobe(self, msg: DProbe) -> None:
        if not self._enter_wave(msg.wave, msg.fence):
            return
        ws = self.ws
        if ws is None or ws.wave != msg.wave:
            return
        if msg.round_id > ws.probe_round_seen:
            ws.probe_round_seen = msg.round_id
        for child in self.tree.children(self._me):
            self._send_dist(
                child,
                wire.encode_dprobe(msg.wave, msg.round_id, self._me, self.fence),
                DProbe(msg.wave, msg.round_id, self._me, self.fence),
            )
        self._pump(ws)

    def _on_dstat(self, msg: DStat) -> None:
        if msg.fence != self.fence:
            self._adopt_fence(msg.fence)
            return  # another era's rounds never merge into this one's
        ws = self.ws
        if ws is None or ws.wave != msg.wave:
            if (
                (ws is None or ws.wave > msg.wave)
                and msg.wave <= self._last_wave_done
            ):
                # A straggler still in a wave we completed: its dfin
                # was lost — re-serve it point-to-point.
                self._send_dist(
                    msg.origin,
                    wire.encode_dfin(msg.wave, self.fence, self._me),
                    DFin(msg.wave, self.fence, self._me),
                )
            return
        ws.child_stats.setdefault(msg.round_id, {})[msg.origin] = msg.stats
        self._pump(ws)

    def _flush_stat_report(self, ws: _WaveState) -> None:
        """Non-root: once LOCALLY SETTLED with every child's aggregate
        for the newest known round in, fold our own stats and push the
        subtree aggregate up the tree.  Settle-gating is what lets the
        root converge in ~2 rounds: a report always describes a locally
        quiescent subtree, so the first judged round after global
        quiescence is already quiet and the second confirms it."""
        if self.tree is None or self._is_root():
            return
        r = ws.probe_round_seen
        if r <= ws.reported_round or not ws.settled():
            return
        children = self.tree.children(self._me)
        got = ws.child_stats.get(r, {})
        if any(c not in got for c in children):
            return
        agg = self._own_stats(ws)
        for c in children:
            self._merge_stats(agg, got[c])
        parent = self.tree.parent(self._me)
        if parent is not None:
            self._send_dist(
                parent,
                wire.encode_dstat(ws.wave, r, self._me, agg, self.fence),
                DStat(ws.wave, r, self._me, agg, self.fence),
            )
        ws.reported_round = r

    def _send_probe(self, ws: _WaveState) -> None:
        for child in self.tree.children(self._me):
            self._send_dist(
                child,
                wire.encode_dprobe(
                    ws.wave, ws.probe_round, self._me, self.fence
                ),
                DProbe(ws.wave, ws.probe_round, self._me, self.fence),
            )

    def _judge_round(self, ws: _WaveState, r: int, agg: dict) -> None:
        """Judge one completed round at the root.  Termination: two
        consecutive judged rounds whose aggregates are all-settled with
        ``sent == recv`` AND an identical (sent, recv) signature.
        Sound by the four-counter argument over idempotent cumulative
        mark sets: during a wave the only sources of new local work are
        received marks (recv grows) and the wave's own seeding, so
        unchanged counters across two all-settled collections mean no
        node did or can do anything between them — global fixpoint."""
        ws.round_done[r] = True
        ws.rounds_run += 1
        self.rounds_total += 1
        events.recorder.commit(
            events.DIST_ROUND,
            wave=ws.wave,
            round=r,
            node=self._me,
            **{k: agg[k] for k in ("settled", "changed", "sent", "recv", "nodes")},
        )
        quiet = (
            agg["settled"]
            and agg["sent"] == agg["recv"]
            and agg["nodes"] == len(self.pmap.members)
        )
        sig = (agg["sent"], agg["recv"])
        # Single-round shortcut, sound ONLY at sent == recv == 0: a
        # settled report means an empty queue, queues grow only by
        # receiving marks, and receiving requires someone to have
        # queued a send — zero global sends at every report time means
        # none can ever occur.  (Nonzero totals genuinely need the
        # second confirming round: a mark can circulate behind the
        # report times and balance the counters by coincidence.)
        if quiet and (sig == (0, 0) or ws.quiet_sig == sig):
            ws.fin = True
            for peer in self.remote_gcs:
                self._send_dist(
                    peer,
                    wire.encode_dfin(ws.wave, ws.fence, self._me),
                    DFin(ws.wave, ws.fence, self._me),
                )
            return
        ws.quiet_sig = sig if quiet else None

    def _root_termination(self, ws: _WaveState) -> None:
        """Event-driven root judge: rounds open when the root itself is
        settled, complete as reports arrive (piggybacked on dmacks or
        explicit dstats), and the next round's probe goes out the
        moment the previous one is judged — round latency is message
        latency, with the per-wake dwave/dprobe re-sends as the
        drop-healing fallback timer."""
        if ws.fin or self.tree is None:
            return
        children = self.tree.children(self._me)
        if ws.probe_round == 0:
            if not ws.settled():
                return
            ws.probe_round = 1
            self._send_probe(ws)
        if not children:
            # Degenerate single-member tree: judge our own stats; the
            # second identical quiet round lands immediately.
            for _ in range(2):
                if ws.fin:
                    break
                r = ws.probe_round
                self._judge_round(ws, r, self._own_stats(ws))
                if not ws.fin:
                    ws.probe_round = r + 1
            return
        while not ws.fin:
            r = ws.probe_round
            got = ws.child_stats.get(r, {})
            if any(c not in got for c in children):
                return  # waiting on reports; the wake re-probe heals
            agg = self._own_stats(ws)
            for c in children:
                self._merge_stats(agg, got[c])
            self._judge_round(ws, r, agg)
            if not ws.fin:
                ws.probe_round = r + 1
                self._send_probe(ws)

    def _on_dfin(self, msg: DFin) -> None:
        if msg.fence > self.fence:
            # Our era lags; adopting resets the wave plane, so there is
            # no wave state left for this fin to close — the sender's
            # next wave (in the adopted era) covers the sweep.
            self._adopt_fence(msg.fence)
            return
        ws = self.ws
        if ws is None or ws.wave != msg.wave or ws.fence != msg.fence:
            return
        ws.fin = True
        # Sweep NOW, not on the next timer wake: the root's next dwave
        # may already be behind this frame in the stream, and entering
        # it would supersede (and silently skip) this wave's sweep.
        n_garbage = self._sweep(ws)
        self._after_wake(n_garbage)
        self._chain_after_sweep()

    # -- sweep ------------------------------------------------------- #

    def _sweep(self, ws: _WaveState) -> int:
        g = self._graph()
        me = self._me
        with events.recorder.timed(events.TRACING) as ev:
            garbage: List[Any] = []
            kills: List[Any] = []
            gates: Dict[str, List] = {}
            num_live = 0
            for shadow in list(g.from_set):
                if not self._owned(shadow):
                    continue
                if shadow in ws.marked:
                    num_live += 1
                    continue
                garbage.append(shadow)
                if shadow.is_halted:
                    continue
                sup = shadow.supervisor
                if sup is None:
                    continue
                if sup in ws.marked:
                    kills.append(shadow.self_cell)
                elif not self._owned(sup):
                    # The supervisor's authoritative mark lives at its
                    # owner: ask it to gate (and dispatch) the kill.
                    owner = self.pmap.owner_of(cell_key(sup.self_cell))
                    if owner is not None and owner != me:
                        gates.setdefault(owner, []).append(
                            (cell_key(sup.self_cell), cell_key(shadow.self_cell))
                        )
            gate_children = set()
            for pairs in gates.values():
                for _sup, child in pairs:
                    gate_children.add(child)
            # Remove decided garbage; keep gate-pending children so the
            # next wave re-derives (and re-gates) them if the decision
            # frame is lost.
            dead = set()
            for shadow in garbage:
                if cell_key(shadow.self_cell) in gate_children:
                    continue
                dead.add(shadow)
                g.drop_shadow(shadow.self_cell)
            # Mirror hygiene: keep only mirrors the surviving owned
            # slice still references.
            referenced = set()
            for shadow in g.from_set:
                if shadow in dead or not self._owned(shadow):
                    continue
                for target, count in shadow.outgoing.items():
                    if count > 0:
                        referenced.add(target)
                sup = shadow.supervisor
                if sup is not None:
                    referenced.add(sup)
            new_from = []
            for shadow in g.from_set:
                if shadow in dead:
                    continue
                if not self._owned(shadow) and shadow not in referenced:
                    g.drop_shadow(shadow.self_cell)
                    continue
                new_from.append(shadow)
            g.from_set = new_from
            # Decayed mirrors follow the same hygiene: once no owned
            # edge references one, its shadow_map pin goes too.
            for cell in [
                c for c, s in g.evicted.items() if s not in referenced
            ]:
                g.evicted.pop(cell, None)
                g.shadow_map.pop(cell, None)
            dispatch_kills(kills)
            # Count only actors actually removed this wave: a
            # gate-pending child stays in the graph for the dgate retry
            # and is re-derived every wave until the decision lands, so
            # counting `garbage` would tally it once per retry.
            n_garbage = len(dead)
            ev.fields["num_garbage_actors"] = n_garbage
            ev.fields["num_gate_pending"] = len(gate_children)
            ev.fields["num_live_actors"] = num_live
        # Locality audit: every content-bearing fold since the last
        # sweep must have landed in our own slice.
        bad = g.audit_fold_locality()
        if bad:
            events.recorder.commit(
                events.DIST_LOCALITY,
                node=me,
                keys=[f"{a}#{u}" for a, u in bad[:8]],
                count=len(bad),
            )
        g.boundary_edge_count()
        # Gates: remembered outside the wave state; unacked gates keep
        # the graph dirty so the next wave retries the decision.
        self._gates_wave = ws.wave
        self._gates_out = gates
        self._gates_acked = {}
        self._resend_gates()
        if gates:
            self._graph_dirty = True
        self._last_marked = {
            cell_key(s.self_cell) for s in ws.marked if self._owned(s)
        }
        san = getattr(self.engine.system, "sanitizer", None)
        if san is not None:
            # Distributed uigcsan: per-node oracles cannot judge a
            # cross-node cycle alone — record this sweep's verdicts for
            # the merged-oracle cross-check
            # (analysis.sanitizer.cross_check_distributed).
            san.note_dist_sweep(
                ws.wave,
                [cell_key(s.self_cell) for s in garbage],
                self._last_marked,
            )
        self._last_wave_done = ws.wave
        self.ws = None
        self.waves_completed += 1
        self.total_dist_garbage += n_garbage
        events.recorder.commit(
            events.DIST_WAVE,
            wave=ws.wave,
            node=me,
            garbage=n_garbage,
            gate_pending=len(gate_children),
            live=num_live,
            rounds=ws.rounds_run,
            marks_sent=ws.sent_total(),
            marks_recv=ws.recv_total(),
            boundary_edges=g.boundary_edges,
        )
        self._fold_pending()
        # With the wave closed and every deferred fold landed, the
        # retained journals can be judged against graph state.
        self._compact_retained()
        g.decay_mirrors(self.mirror_decay)
        return n_garbage

    def _compact_retained(self) -> None:
        """Amortized prune of the per-partition absorb journals —
        without it they pin every cell the node ever generated a fact
        about, an unbounded leak inside the collector itself.  Dropped:
        facts about provably-dead actors (locally terminated cells, and
        owned keys our own sweep already removed from the graph) and
        zero-information touch residue.  Leak-safe by construction —
        pruning a fact can only make a re-folded actor look MORE alive,
        never less (the same argument the absorb path's 'a dead node's
        facts die with it' rests on).  A journal compacts when it
        doubled since its last compaction, so the cost stays
        proportional to growth.  Must run only with no wave in flight
        and no pending folds: a live owned actor whose facts sit in
        _pending_deltas is not yet in key_index and would be judged
        dead."""
        pmap = self.pmap
        if pmap is None:
            return
        key_index = self._graph().key_index

        def keep(cell: Any, sh: Any) -> bool:
            if getattr(cell, "is_terminated", False):
                return False
            key = cell_key(cell)
            if pmap.owns(key) and key not in key_index:
                return False  # swept out of our own authoritative slice
            if (
                not sh.interned
                and not sh.outgoing
                and sh.recv_count == 0
                and sh.supervisor < 0
                and not sh.is_root
                and not sh.is_busy
            ):
                return False  # pure touch residue; re-created on demand
            return True

        for p, journal in list(self._retained.items()):
            size = journal.size
            if size < 64 or size < 2 * self._retained_floor.get(p, 0):
                continue
            compacted = journal.compact(keep)
            self._retained[p] = compacted
            self._retained_floor[p] = compacted.size

    def _resend_gates(self) -> None:
        for peer, pairs in self._gates_out.items():
            if self._gates_acked.get(peer, 0) >= len(pairs):
                continue
            self._send_dist(
                peer,
                wire.encode_dgate(self._gates_wave, self.fence, self._me, pairs),
                DGate(self._gates_wave, self.fence, self._me, list(pairs)),
            )

    def _on_dgate(self, msg: DGate) -> None:
        """Serve a peer's kill gate from our authoritative marks for
        that wave: a live (marked) supervisor means the child is the
        oldest unmarked ancestor — dispatch its StopMsg from here; an
        unmarked supervisor means our own sweep's cascade covers it.
        Idempotent: re-processed pairs are skipped, the cumulative ack
        heals lost acks."""
        if msg.fence > self.fence:
            # Era lag: adopt (resets our marks) — judging with old-era
            # marks could kill against stale ownership.  The sender's
            # unacked gate keeps its graph dirty; its next wave in the
            # adopted era re-derives and re-gates the decision.
            self._adopt_fence(msg.fence)
            return
        if msg.fence != self.fence:
            return
        marks: Optional[Set[Tuple[str, int]]] = None
        ws = self.ws
        if ws is not None and ws.wave == msg.wave:
            marks = {
                cell_key(s.self_cell) for s in ws.marked if self._owned(s)
            }
        elif self._last_wave_done == msg.wave:
            marks = self._last_marked
        if marks is None:
            return  # can't judge this wave; the sender's next wave retries
        seen = self._gates_seen.setdefault((msg.origin, msg.wave), set())
        kills = []
        for sup_key, child_key in msg.pairs:
            pair = (tuple(sup_key), tuple(child_key))
            if pair in seen:
                continue
            seen.add(pair)
            if pair[0] in marks:
                cell = self._resolve_key(pair[1])
                if cell is not None:
                    kills.append(cell)
        dispatch_kills(kills)
        # Bound the dedup memory: one wave back is all a retry can name.
        for key in [k for k in self._gates_seen if k[1] < msg.wave - 1]:
            del self._gates_seen[key]
        self._send_dist(
            msg.origin,
            wire.encode_dgack(msg.wave, self._me, len(seen), self.fence),
            DGack(msg.wave, self._me, len(seen), self.fence),
        )

    def _on_dgack(self, msg: DGack) -> None:
        if msg.fence != self.fence:
            self._adopt_fence(msg.fence)
            return
        if msg.wave != self._gates_wave:
            return
        prev = self._gates_acked.get(msg.origin, 0)
        if msg.count > prev:
            self._gates_acked[msg.origin] = msg.count

    # -- diagnostics -------------------------------------------------- #

    def diagnostic_dump(self) -> Dict[str, Any]:
        out = super().diagnostic_dump()
        g = self._graph()
        out["distributed"] = {
            "fence": self.fence,
            "wave": self.wave,
            "waves_completed": self.waves_completed,
            "garbage_total": self.total_dist_garbage,
            "marks_sent": self.marks_sent,
            "mark_bytes": self.mark_bytes,
            "marks_received": self.marks_received,
            "rounds_total": self.rounds_total,
            "owned_partitions": (
                self.pmap.owned_partitions() if self.pmap is not None else []
            ),
            "owned_population": g.owned_population(),
            "population": len(g.from_set),
            "boundary_edges": g.boundary_edges,
            "mirrors_evicted": len(g.evicted),
            "mirrors_evicted_total": g.mirrors_evicted_total,
        }
        return out
