"""Decompose the Pallas trace's per-sweep cost at graph scale.

Times three things the full fixpoint mixes together (bench.py reports
only their sum across ~12 sweeps):

- a **full-dirty** propagation sweep (every chunk dirty: worst-case walk
  + every block's one-hot contraction);
- a **no-dirty** sweep (empty dirty list: pure grid/stream overhead —
  every block still streams its row_pos/emeta and runs the skip branch);
- the **word-space pack2d** of per-sweep hits into the word table (the
  per-sweep XLA cost outside the kernel), plus the legacy O(n)
  bool-space pack (now paid only once per trace, for seed/gate vectors).

Prints one JSON line.  Usage: python tools/sweep_profile.py [--n 10000000]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _sync(out):
    """Force completion via a 1-element readback: on the axon transport
    ``block_until_ready`` returns before the program finishes — only a
    value readback actually synchronizes."""
    import jax

    leaves = jax.tree_util.tree_leaves(out)
    for leaf in leaves:
        jax.device_get(leaf.ravel()[0])


def timed(fn, *args, reps=5):
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts) * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--small", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from uigc_tpu.models import powerlaw_actor_graph
    from uigc_tpu.ops import pallas_trace as pt
    from uigc_tpu.utils.platform import apply_platform_override, is_tpu_platform

    apply_platform_override()
    on_tpu = is_tpu_platform(jax.devices()[0].platform)
    n = args.n or (10_000_000 if on_tpu and not args.small else 1 << 16)

    sub, group = pt.default_geometry()
    # Cache keyed by geometry and the packer's own format version, in a
    # per-user dir (a fixed /tmp name could collide with another user's
    # files on a shared host).
    import os
    import tempfile

    cache_dir = Path(tempfile.gettempdir()) / f"uigc_prep_{os.getuid()}"
    cache_dir.mkdir(exist_ok=True)
    # The key carries the graph model's identity (version + generator
    # params), not just the pack format: a generator change must miss,
    # or the benchmark silently measures a stale graph.
    from uigc_tpu.models import graphgen

    seed, frac = 0, 0.5
    cache = cache_dir / (
        f"v{pt.PACK_FORMAT_VERSION}_g{graphgen.GRAPH_MODEL_VERSION}"
        f"_s{seed}_f{frac}_{n}_{pt.S_ROWS}_{sub}_{group}.npz"
    )
    # One-time migration: the pre-model-keyed cache name for the same
    # (unchanged, version-1) generator.
    legacy = cache_dir / (
        f"v{pt.PACK_FORMAT_VERSION}_{n}_{pt.S_ROWS}_{sub}_{group}.npz"
    )
    if graphgen.GRAPH_MODEL_VERSION == 1 and legacy.exists() and not cache.exists():
        os.replace(legacy, cache)
    prep = None
    if cache.exists():
        try:
            z = np.load(cache)
            prep = {k: (z[k] if z[k].ndim else z[k].item()) for k in z.files}
            pack_host_s = None  # cache hit: not measured this run
        except Exception:
            cache.unlink(missing_ok=True)  # poisoned cache: repack
    if prep is None:
        graph = powerlaw_actor_graph(n, seed=seed, garbage_fraction=frac)
        t0 = time.perf_counter()
        prep = pt.prepare_chunks(
            graph["edge_src"].astype(np.int32),
            graph["edge_dst"].astype(np.int32),
            graph["edge_weight"],
            graph["supervisor"],
            n,
        )
        pack_host_s = time.perf_counter() - t0
        # Atomic publish: a run interrupted mid-savez must not leave a
        # truncated npz at the final path (np.load would BadZipFile on
        # every later run).
        tmp = cache.with_suffix(".tmp.npz")
        np.savez(tmp, **prep)
        os.replace(tmp, cache)
    r_rows, s_rows, n_super = prep["r_rows"], prep["s_rows"], prep["n_super"]
    n_blocks = prep["n_blocks"]
    n_chunks = r_rows // (pt.ROWS * prep["group"])

    propagate = pt.build_propagate(
        n_blocks, n_super, r_rows, s_rows, pt.default_interpret(),
        sub=prep["sub"], group=prep["group"],
    )
    dev = {
        k: jax.device_put(prep[k])
        for k in ("bmeta1", "bmeta2", "row_pos", "emeta")
    }

    rng = np.random.default_rng(0)
    table = jax.device_put(
        rng.integers(0, 1 << 31, (r_rows, pt.LANE), dtype=np.int32)
    )
    d_full = jax.device_put(np.arange(n_chunks + 1, dtype=np.int32))
    l_full = jax.device_put(np.arange(n_chunks, dtype=np.int32))
    d_none = jax.device_put(np.zeros(n_chunks + 1, dtype=np.int32))

    full_ms = timed(
        propagate, d_full, l_full, dev["bmeta1"], dev["bmeta2"], table,
        dev["row_pos"], dev["emeta"],
    )
    none_ms = timed(
        propagate, d_none, l_full, dev["bmeta1"], dev["bmeta2"], table,
        dev["row_pos"], dev["emeta"],
    )

    # half the chunks dirty (even ids): the mid-fixpoint regime
    diff = np.zeros(n_chunks, bool)
    diff[::2] = True
    dd = np.concatenate([[0], np.cumsum(diff)]).astype(np.int32)
    ll = np.zeros(n_chunks, np.int32)
    ll[dd[:-1][diff]] = np.nonzero(diff)[0].astype(np.int32)
    half_ms = timed(
        propagate, jax.device_put(dd), jax.device_put(ll), dev["bmeta1"],
        dev["bmeta2"], table, dev["row_pos"], dev["emeta"],
    )

    shifts = jnp.arange(pt.WORD_BITS, dtype=jnp.int32)

    @jax.jit
    def pack(active):
        a = jnp.zeros(r_rows * pt.LANE * pt.WORD_BITS, jnp.int32)
        a = a.at[:n].set(active.astype(jnp.int32))
        w = (a.reshape(-1, pt.WORD_BITS) << shifts[None, :]).sum(
            axis=1, dtype=jnp.int32
        )
        return w.reshape(r_rows, pt.LANE)

    active = jax.device_put(np.ones(n, bool))
    pack_ms = timed(pack, active)

    # The per-sweep pack actually on the fixpoint path now: word-space
    # pack2d of a (t_rows, LANE) hits plane (pallas_trace trace_fn).
    t_rows = n_super * s_rows

    @jax.jit
    def pack2d(hits2d):
        return pt.pack_hits_table(hits2d, r_rows, jnp)

    hits2d = jax.device_put(np.ones((t_rows, pt.LANE), bool))
    pack2d_ms = timed(pack2d, hits2d)

    print(
        json.dumps(
            {
                "bench": "sweep_profile",
                "n_actors": n,
                "n_blocks": n_blocks,
                "n_chunks": n_chunks,
                "n_pairs": prep["n_pairs"],
                "host_pack_s": (
                    round(pack_host_s, 2) if pack_host_s is not None else None
                ),
                "sweep_full_dirty_ms": round(full_ms, 2),
                "sweep_half_dirty_ms": round(half_ms, 2),
                "sweep_no_dirty_ms": round(none_ms, 2),
                "pack_seed_ms": round(pack_ms, 2),
                "pack2d_per_sweep_ms": round(pack2d_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
