"""Decompose the Pallas trace's per-sweep cost at graph scale.

Times three things the full fixpoint mixes together (bench.py reports
only their sum across ~12 sweeps):

- a **full-dirty** propagation sweep (every chunk dirty: worst-case walk
  + every block's one-hot contraction);
- a **no-dirty** sweep (empty dirty list: pure grid/stream overhead —
  every block still streams its row_pos/emeta and runs the skip branch);
- the **pack** of the mark vector into the word table (per-sweep XLA
  cost outside the kernel).

Prints one JSON line.  Usage: python tools/sweep_profile.py [--n 10000000]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def timed(fn, *args, reps=5):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts) * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--small", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from uigc_tpu.models import powerlaw_actor_graph
    from uigc_tpu.ops import pallas_trace as pt
    from uigc_tpu.utils.platform import apply_platform_override, is_tpu_platform

    apply_platform_override()
    on_tpu = is_tpu_platform(jax.devices()[0].platform)
    n = args.n or (10_000_000 if on_tpu and not args.small else 1 << 16)

    graph = powerlaw_actor_graph(n, seed=0, garbage_fraction=0.5)
    t0 = time.perf_counter()
    prep = pt.prepare_chunks(
        graph["edge_src"].astype(np.int32),
        graph["edge_dst"].astype(np.int32),
        graph["edge_weight"],
        graph["supervisor"],
        n,
    )
    pack_host_s = time.perf_counter() - t0
    r_rows, s_rows, n_super = prep["r_rows"], prep["s_rows"], prep["n_super"]
    n_blocks = prep["n_blocks"]
    n_chunks = r_rows // pt.ROWS

    propagate = pt.build_propagate(
        n_blocks, n_super, r_rows, s_rows, pt.default_interpret()
    )
    dev = {
        k: jax.device_put(prep[k])
        for k in ("bmeta1", "bmeta2", "row_pos", "emeta")
    }

    rng = np.random.default_rng(0)
    table = jax.device_put(
        rng.integers(0, 1 << 31, (r_rows, pt.LANE), dtype=np.int32)
    )
    d_full = jax.device_put(np.arange(n_chunks + 1, dtype=np.int32))
    l_full = jax.device_put(np.arange(n_chunks, dtype=np.int32))
    d_none = jax.device_put(np.zeros(n_chunks + 1, dtype=np.int32))

    full_ms = timed(
        propagate, d_full, l_full, dev["bmeta1"], dev["bmeta2"], table,
        dev["row_pos"], dev["emeta"],
    )
    none_ms = timed(
        propagate, d_none, l_full, dev["bmeta1"], dev["bmeta2"], table,
        dev["row_pos"], dev["emeta"],
    )

    # half the chunks dirty (even ids): the mid-fixpoint regime
    diff = np.zeros(n_chunks, bool)
    diff[::2] = True
    dd = np.concatenate([[0], np.cumsum(diff)]).astype(np.int32)
    ll = np.zeros(n_chunks, np.int32)
    ll[dd[:-1][diff]] = np.nonzero(diff)[0].astype(np.int32)
    half_ms = timed(
        propagate, jax.device_put(dd), jax.device_put(ll), dev["bmeta1"],
        dev["bmeta2"], table, dev["row_pos"], dev["emeta"],
    )

    shifts = jnp.arange(pt.WORD_BITS, dtype=jnp.int32)

    @jax.jit
    def pack(active):
        a = jnp.zeros(r_rows * pt.LANE * pt.WORD_BITS, jnp.int32)
        a = a.at[:n].set(active.astype(jnp.int32))
        w = (a.reshape(-1, pt.WORD_BITS) << shifts[None, :]).sum(
            axis=1, dtype=jnp.int32
        )
        return w.reshape(r_rows, pt.LANE)

    active = jax.device_put(np.ones(n, bool))
    pack_ms = timed(pack, active)

    print(
        json.dumps(
            {
                "bench": "sweep_profile",
                "n_actors": n,
                "n_blocks": n_blocks,
                "n_chunks": n_chunks,
                "n_pairs": prep["n_pairs"],
                "host_pack_s": round(pack_host_s, 2),
                "sweep_full_dirty_ms": round(full_ms, 2),
                "sweep_half_dirty_ms": round(half_ms, 2),
                "sweep_no_dirty_ms": round(none_ms, 2),
                "pack_table_ms": round(pack_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
