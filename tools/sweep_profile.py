"""Decompose the Pallas trace's per-sweep cost at graph scale.

Times three things the full fixpoint mixes together (bench.py reports
only their sum across ~12 sweeps):

- a **full-dirty** propagation sweep (every chunk dirty: worst-case walk
  + every block's one-hot contraction);
- a **no-dirty** sweep (empty dirty list: pure grid/stream overhead —
  every block still streams its row_pos/emeta and runs the skip branch);
- the **word-space pack2d** of per-sweep hits into the word table (the
  per-sweep XLA cost outside the kernel), plus the legacy O(n)
  bool-space pack (now paid only once per trace, for seed/gate vectors).

Plus, per trace mode (uigc.crgc.trace-mode: push/pull/jump/auto), the
**per-sweep frontier decomposition** of the real fixpoint — sweep
count, dirty-chunk density, supertiles changed, tiles pull-skipped,
and the auto mode's per-sweep pull decision — emitted through the
telemetry wake profiler (telemetry/profile.py), so the pull-density
threshold is tuned from recorded wake data instead of guessed.

``--simulate`` instead runs the numpy sweep-count simulation at the
same graph geometry: sweep counts are hardware-independent, so the
push-vs-jump convergence (O(diameter) vs O(log diameter) sweeps) is
measurable without a chip — the number the ISSUE-6 acceptance
criterion is judged against.

Prints one JSON line.  Usage: python tools/sweep_profile.py [--n 10000000]
       [--simulate] [--modes auto,push,pull,jump] [--skip-probes]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _sync(out):
    """Force completion via a 1-element readback: on the axon transport
    ``block_until_ready`` returns before the program finishes — only a
    value readback actually synchronizes."""
    import jax

    leaves = jax.tree_util.tree_leaves(out)
    for leaf in leaves:
        jax.device_get(leaf.ravel()[0])


def timed(fn, *args, reps=5):
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts) * 1e3


def simulate_sweeps(graph, n, modes, jump_steps=None):
    """Hardware-independent fixpoint sweep counts per trace mode, by
    direct numpy simulation of the kernel's per-sweep semantics
    (pallas_trace trace_fn: table = mark & ~halted, hits gated by
    in_use, jump parents squared ``JUMP_STEPS`` times per sweep through
    transparent intermediates).  Pull gating changes per-sweep WORK,
    never the sweep count, so pull reports push's count and auto
    jump's."""
    from uigc_tpu.ops import pallas_trace as pt
    from uigc_tpu.ops import trace as trace_ops

    F = trace_ops
    if jump_steps is None:
        jump_steps = pt.JUMP_STEPS
    flags = graph["flags"]
    recv = graph["recv_count"]
    live = graph["edge_weight"] > 0
    psrc = graph["edge_src"][live].astype(np.int64)
    pdst = graph["edge_dst"][live].astype(np.int64)
    sup = graph["supervisor"]
    sup_src = np.nonzero(sup >= 0)[0].astype(np.int64)
    psrc = np.concatenate([psrc, sup_src])
    pdst = np.concatenate([pdst, sup[sup_src].astype(np.int64)])

    in_use = (flags & F.FLAG_IN_USE) != 0
    halted = (flags & F.FLAG_HALTED) != 0
    seed = (
        ((flags & F.FLAG_ROOT) != 0)
        | ((flags & F.FLAG_BUSY) != 0)
        | (recv != 0)
        | ((flags & F.FLAG_INTERNED) == 0)
    )
    mark0 = in_use & (~halted) & seed
    trans = in_use & (~halted)
    trans_pad = np.concatenate([trans, [False]])

    counts = {}
    # Pull gating changes per-sweep work, never the sweep count, so
    # only the push/jump variants are actually simulated and the other
    # modes alias their counts.
    aliases = {pt.MODE_PULL: pt.MODE_PUSH, pt.MODE_AUTO: pt.MODE_JUMP}
    for mode in modes:
        src = aliases.get(mode, mode)
        if src in counts:
            continue
        use_jump = src == pt.MODE_JUMP
        j = pt.jump_parents(psrc, pdst, n) if use_jump else None
        mark = mark0.copy()
        sweeps = 0
        while True:
            sweeps += 1
            active = mark & ~halted
            new = mark.copy()
            hit_dst = pdst[active[psrc]]
            new[hit_dst] |= in_use[hit_dst]
            if use_jump:
                active_pad = np.concatenate([active, [False]])
                jh = active_pad[j[:n]] & in_use
                new |= jh
                for _ in range(jump_steps):
                    j2 = j[j]
                    can = trans_pad[j] & (j2 < n)
                    j = np.where(can, j2, j)
            if np.array_equal(new, mark):
                break
            mark = new
        # the device fixpoint's sweep count includes the final
        # no-change sweep that proves convergence — same convention
        counts[src] = sweeps
    return {m: counts[aliases.get(m, m)] for m in modes}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--small", action="store_true")
    ap.add_argument(
        "--simulate", action="store_true",
        help="numpy sweep-count simulation per mode (no device work)",
    )
    ap.add_argument(
        "--modes", default="push,pull,jump,auto",
        help="comma-separated trace modes for the fixpoint decomposition",
    )
    ap.add_argument(
        "--skip-probes", action="store_true",
        help="skip the isolated-sweep probes (fixpoint decomposition only)",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from uigc_tpu.models import powerlaw_actor_graph
    from uigc_tpu.ops import pallas_trace as pt
    from uigc_tpu.utils.platform import apply_platform_override, is_tpu_platform

    apply_platform_override()
    on_tpu = is_tpu_platform(jax.devices()[0].platform)
    n = args.n or (10_000_000 if on_tpu and not args.small else 1 << 16)
    seed, frac = 0, 0.5

    if args.simulate:
        # Sweep counts are hardware-independent: pure numpy, no device.
        graph = powerlaw_actor_graph(n, seed=seed, garbage_fraction=frac)
        modes = [m.strip() for m in args.modes.split(",") if m.strip()]
        counts = simulate_sweeps(graph, n, modes)
        print(
            json.dumps(
                {
                    "bench": "sweep_profile_simulate",
                    "n_actors": n,
                    "n_pairs": int(
                        (graph["edge_weight"] > 0).sum()
                        + (graph["supervisor"] >= 0).sum()
                    ),
                    "jump_steps": pt.JUMP_STEPS,
                    "sweeps": counts,
                }
            )
        )
        return

    sub, group = pt.default_geometry()
    # Cache keyed by geometry and the packer's own format version, in a
    # per-user dir (a fixed /tmp name could collide with another user's
    # files on a shared host).
    import os
    import tempfile

    cache_dir = Path(tempfile.gettempdir()) / f"uigc_prep_{os.getuid()}"
    cache_dir.mkdir(exist_ok=True)
    # The key carries the graph model's identity (version + generator
    # params), not just the pack format: a generator change must miss,
    # or the benchmark silently measures a stale graph.
    from uigc_tpu.models import graphgen

    cache = cache_dir / (
        f"v{pt.PACK_FORMAT_VERSION}_g{graphgen.GRAPH_MODEL_VERSION}"
        f"_s{seed}_f{frac}_{n}_{pt.S_ROWS}_{sub}_{group}.npz"
    )
    # One-time migration: the pre-model-keyed cache name for the same
    # (unchanged, version-1) generator.
    legacy = cache_dir / (
        f"v{pt.PACK_FORMAT_VERSION}_{n}_{pt.S_ROWS}_{sub}_{group}.npz"
    )
    if graphgen.GRAPH_MODEL_VERSION == 1 and legacy.exists() and not cache.exists():
        os.replace(legacy, cache)
    #: node features + jump parents ride a sibling cache so the
    #: fixpoint decomposition needs no graph regen on a prep-cache hit
    aux_cache = cache.with_suffix(".aux.npz")
    prep = None
    graph = None
    if cache.exists():
        try:
            z = np.load(cache)
            prep = {k: (z[k] if z[k].ndim else z[k].item()) for k in z.files}
            pack_host_s = None  # cache hit: not measured this run
        except Exception:
            cache.unlink(missing_ok=True)  # poisoned cache: repack
    if prep is None:
        graph = powerlaw_actor_graph(n, seed=seed, garbage_fraction=frac)
        t0 = time.perf_counter()
        prep = pt.prepare_chunks(
            graph["edge_src"].astype(np.int32),
            graph["edge_dst"].astype(np.int32),
            graph["edge_weight"],
            graph["supervisor"],
            n,
        )
        pack_host_s = time.perf_counter() - t0
        # Atomic publish: a run interrupted mid-savez must not leave a
        # truncated npz at the final path (np.load would BadZipFile on
        # every later run).
        tmp = cache.with_suffix(".tmp.npz")
        np.savez(tmp, **prep)
        os.replace(tmp, cache)

    aux = None
    if aux_cache.exists():
        try:
            z = np.load(aux_cache)
            aux = {k: z[k] for k in z.files}
        except Exception:
            aux_cache.unlink(missing_ok=True)
    if aux is None:
        if graph is None:
            graph = powerlaw_actor_graph(n, seed=seed, garbage_fraction=frac)
        aux = {
            "flags": graph["flags"],
            "recv": graph["recv_count"],
            "jump_parent": pt.jump_parents_from_graph(
                graph["edge_src"], graph["edge_dst"],
                graph["edge_weight"], graph["supervisor"], n,
            ),
        }
        tmp = aux_cache.with_suffix(".tmp.npz")
        np.savez(tmp, **aux)
        os.replace(tmp, aux_cache)
    r_rows, s_rows, n_super = prep["r_rows"], prep["s_rows"], prep["n_super"]
    n_blocks = prep["n_blocks"]
    n_chunks = r_rows // (pt.ROWS * prep["group"])

    full_ms = none_ms = half_ms = pack_ms = pack2d_ms = None
    if not args.skip_probes:
        propagate = pt.build_propagate(
            n_blocks, n_super, r_rows, s_rows, pt.default_interpret(),
            sub=prep["sub"], group=prep["group"],
        )
        dev = {
            k: jax.device_put(prep[k])
            for k in ("bmeta1", "bmeta2", "row_pos", "emeta")
        }

        rng = np.random.default_rng(0)
        table = jax.device_put(
            rng.integers(0, 1 << 31, (r_rows, pt.LANE), dtype=np.int32)
        )
        d_full = jax.device_put(np.arange(n_chunks + 1, dtype=np.int32))
        l_full = jax.device_put(np.arange(n_chunks, dtype=np.int32))
        d_none = jax.device_put(np.zeros(n_chunks + 1, dtype=np.int32))

        full_ms = timed(
            propagate, d_full, l_full, dev["bmeta1"], dev["bmeta2"], table,
            dev["row_pos"], dev["emeta"],
        )
        none_ms = timed(
            propagate, d_none, l_full, dev["bmeta1"], dev["bmeta2"], table,
            dev["row_pos"], dev["emeta"],
        )

        # half the chunks dirty (even ids): the mid-fixpoint regime
        diff = np.zeros(n_chunks, bool)
        diff[::2] = True
        dd = np.concatenate([[0], np.cumsum(diff)]).astype(np.int32)
        ll = np.zeros(n_chunks, np.int32)
        ll[dd[:-1][diff]] = np.nonzero(diff)[0].astype(np.int32)
        half_ms = timed(
            propagate, jax.device_put(dd), jax.device_put(ll), dev["bmeta1"],
            dev["bmeta2"], table, dev["row_pos"], dev["emeta"],
        )

        shifts = jnp.arange(pt.WORD_BITS, dtype=jnp.int32)

        @jax.jit
        def pack(active):
            a = jnp.zeros(r_rows * pt.LANE * pt.WORD_BITS, jnp.int32)
            a = a.at[:n].set(active.astype(jnp.int32))
            w = (a.reshape(-1, pt.WORD_BITS) << shifts[None, :]).sum(
                axis=1, dtype=jnp.int32
            )
            return w.reshape(r_rows, pt.LANE)

        active = jax.device_put(np.ones(n, bool))
        pack_ms = timed(pack, active)

        # The per-sweep pack actually on the fixpoint path now: word-space
        # pack2d of a (t_rows, LANE) hits plane (pallas_trace trace_fn).
        t_rows = n_super * s_rows

        @jax.jit
        def pack2d(hits2d):
            return pt.pack_hits_table(hits2d, r_rows, jnp)

        hits2d = jax.device_put(np.ones((t_rows, pt.LANE), bool))
        pack2d_ms = timed(pack2d, hits2d)

    # --- per-mode fixpoint decomposition, through the wake profiler -- #
    # The same DEVICE_TRACE event fields the engine stamps per wake
    # (engines/crgc/arrays.py _stamp_sweep_stats) flow through a real
    # WakeProfiler here, so this tool exercises — and its JSON matches —
    # the telemetry pipeline the pull-density threshold is tuned from.
    modes = [m.strip() for m in args.modes.split(",") if m.strip()]
    mode_out = {}
    wake_records = None
    if modes:
        from uigc_tpu.telemetry.profile import WakeProfiler
        from uigc_tpu.utils import events

        profiler = WakeProfiler(node="sweep_profile")
        was_enabled = events.recorder.enabled
        events.recorder.enable()
        events.recorder.add_listener(profiler)
        flags_h, recv_h = aux["flags"], aux["recv"]
        jp = aux["jump_parent"]
        try:
            for mode in modes:
                use_jump = mode in (pt.MODE_JUMP, pt.MODE_AUTO)

                def run():
                    return pt.trace_marks_layouts(
                        flags_h, recv_h, [prep],
                        mode=mode,
                        jump_parent=jp if use_jump else None,
                        with_stats=True,
                    )

                wk = profiler.begin_wake()
                with wk.phase("trace"):
                    with events.recorder.timed(events.DEVICE_TRACE) as ev:
                        run()  # compile + warmup
                        t0 = time.perf_counter()
                        _, stats = run()
                        fix_ms = (time.perf_counter() - t0) * 1e3
                        k = int(stats["n_sweeps"])
                        ev.fields["trace_mode"] = mode
                        ev.fields["n_sweeps"] = k
                        ev.fields["sweep_dirty_chunks"] = (
                            stats["dirty_chunks"][:k].tolist()
                        )
                        ev.fields["sweep_changed_supers"] = (
                            stats["changed_supers"][:k].tolist()
                        )
                        ev.fields["sweep_tiles_skipped"] = (
                            stats["tiles_skipped"][:k].tolist()
                        )
                        ev.fields["sweep_pull_on"] = (
                            stats["pull_on"][:k].tolist()
                        )
                wk.end(mode=mode)
                kk = min(k, len(stats["dirty_chunks"]))
                mode_out[mode] = {
                    "n_sweeps": k,
                    "fixpoint_ms": round(fix_ms, 2),
                    "dirty_chunks": stats["dirty_chunks"][:kk].tolist(),
                    "changed_supers": stats["changed_supers"][:kk].tolist(),
                    "tiles_skipped": stats["tiles_skipped"][:kk].tolist(),
                    "pull_on": stats["pull_on"][:kk].tolist(),
                }
        finally:
            events.recorder.remove_listener(profiler)
            if not was_enabled:
                events.recorder.disable()
        wake_records = profiler.to_json()["recent"]

    out = {
        "bench": "sweep_profile",
        "n_actors": n,
        "n_blocks": n_blocks,
        "n_chunks": n_chunks,
        "n_pairs": prep["n_pairs"],
        "host_pack_s": (
            round(pack_host_s, 2) if pack_host_s is not None else None
        ),
        "modes": mode_out,
        "wake_profile_recent": wake_records,
    }
    if not args.skip_probes:
        out.update(
            {
                "sweep_full_dirty_ms": round(full_ms, 2),
                "sweep_half_dirty_ms": round(half_ms, 2),
                "sweep_no_dirty_ms": round(none_ms, 2),
                "pack_seed_ms": round(pack_ms, 2),
                "pack2d_per_sweep_ms": round(pack2d_ms, 2),
            }
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
