"""Chaos bench: failure-detection and recovery latency under a seeded
fault plan (runtime/faults.py + runtime/heartbeat.py).

Spins a three-node cluster in ONE process over real localhost sockets
(the same transport as the multi-process tests, with every node
inspectable), runs actor churn across the links while a seeded
``FaultPlan`` drops/duplicates/reorders/truncates app frames on the
doomed node's links, then kills the doomed node SILENTLY (links muted,
engine stopped, sockets left open — no EOF).  Measures, per seed:

- detection latency: silent death -> heartbeat NODE_DOWN verdict
- finalize latency:  death -> both survivors' dead links finalized
- recovery latency:  death -> undo-log quorum folded on both survivors
- convergence:       time until every surviving recv balance is zero
- wire damage:       frames dropped/duplicated/corrupt, gaps, dead letters

Prints one JSON object; commit as ``BENCH_CHAOS_r{N}.json``.

Usage: python tools/chaos_bench.py [--seeds 3] [--churn 200]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.shadow-graph": "array",
    "uigc.crgc.num-nodes": 3,
    "uigc.node.heartbeat-interval": 40,
    "uigc.node.phi-threshold": 6.0,
    "uigc.node.heartbeat-pause": 400,
}

from uigc_tpu import AbstractBehavior, Behaviors, Message, NoRefs  # noqa: E402


# Message/behavior classes live at module level so the wire codec can
# pickle them (a local class has no importable qualname).


class Ping(NoRefs):
    pass


class Share(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class Drop(NoRefs):
    pass


class Worker(AbstractBehavior):
    def on_message(self, msg):
        return self


class Holder(AbstractBehavior):
    def __init__(self, context):
        super().__init__(context)
        self.held = None

    def on_message(self, msg):
        if isinstance(msg, Share):
            self.held = msg.ref
        if self.held is not None:
            self.held.tell(Ping(), self.context)
        return self


class Owner(AbstractBehavior):
    def __init__(self, context, holder_ref):
        super().__init__(context)
        self.worker = context.spawn(
            Behaviors.setup(lambda c: Worker(c)), "worker"
        )
        self.holder_ref = holder_ref

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Share):
            self.holder_ref.tell(
                Share(ctx.create_ref(self.worker, self.holder_ref)), ctx
            )
        elif isinstance(msg, Drop):
            ctx.release(self.worker)
        return self


def run_seed(seed: int, churn: int) -> dict:
    from uigc_tpu.runtime.faults import FaultPlan
    from uigc_tpu.runtime.node import NodeFabric
    from uigc_tpu.runtime.system import ActorSystem
    from uigc_tpu.utils import events

    plan = FaultPlan(seed)
    names = [f"cb{seed}a", f"cb{seed}b", f"cb{seed}c"]
    fabrics, systems, ports = [], [], []
    for n in names:
        f = NodeFabric(fault_plan=plan)
        s = ActorSystem(None, name=n, config=dict(BASE), fabric=f)
        fabrics.append(f)
        systems.append(s)
        ports.append(f.listen())
    addr = [s.address for s in systems]
    for i in range(3):
        for j in range(i + 1, 3):
            fabrics[i].connect("127.0.0.1", ports[j])

    for src, dst in ((addr[1], addr[2]), (addr[2], addr[1]),
                     (addr[0], addr[2]), (addr[2], addr[0])):
        plan.drop(src=src, dst=dst, kind="app", prob=0.2)
        plan.duplicate(src=src, dst=dst, kind="app", prob=0.2)
        plan.reorder(src=src, dst=dst, kind="app", prob=0.1)
        plan.truncate(src=src, dst=dst, kind="app", prob=0.1)

    marks: dict = {"down": {}, "final": {}, "fold": {}}
    lock = threading.Lock()

    def listener(name, fields):
        now = time.perf_counter()
        with lock:
            if name == events.NODE_DOWN and fields.get("address") == addr[2]:
                marks["down"].setdefault(fields.get("reason"), now)
            elif name == events.DEAD_LINK_FINALIZED and fields.get("src") == addr[2]:
                marks["final"].setdefault(fields.get("dst"), now)
            elif name == events.UNDO_FOLD and fields.get("address") == addr[2]:
                marks["fold"].setdefault(fields.get("node"), now)

    events.recorder.enable()
    events.recorder.add_listener(listener)

    holder = systems[2].spawn_root(
        Behaviors.setup_root(lambda ctx: Holder(ctx)), "holder"
    )
    holder_proxy = fabrics[1]._proxy(addr[2], holder.cell.uid)
    owner = systems[1].spawn_root(
        Behaviors.setup_root(
            lambda ctx: Owner(ctx, ctx.engine.to_root_refob(holder_proxy))
        ),
        "owner",
    )
    owner.tell(Share(None))
    for _ in range(churn):
        holder.tell(Ping())
        time.sleep(0.001)
    owner.tell(Drop())
    time.sleep(0.3)

    # Silent death of node C: no EOF, only heartbeat silence.
    t_kill = time.perf_counter()
    plan.isolate(addr[2])
    systems[2].engine.on_crash()

    def survivors_converged():
        balances_zero = all(
            s.engine.bookkeeper.shadow_graph.investigate_live_set()["nonzero_recv"]
            == 0
            for s in systems[:2]
        )
        with lock:
            folded = len(marks["fold"]) >= 2
        return balances_zero and folded

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not survivors_converged():
        time.sleep(0.02)
    t_conv = time.perf_counter()

    drops = sum(v for k, v in plan.stats.items() if k[0] == "drop")
    dups = sum(v for k, v in plan.stats.items() if k[0] == "duplicate")
    snap = events.recorder.snapshot()["counts"]
    result = {
        "seed": seed,
        "converged": survivors_converged(),
        "detect_s": round(marks["down"].get("heartbeat", t_conv) - t_kill, 3),
        "finalize_s": round(max(marks["final"].values(), default=t_conv) - t_kill, 3),
        "undo_fold_s": round(max(marks["fold"].values(), default=t_conv) - t_kill, 3),
        "converge_s": round(t_conv - t_kill, 3),
        "frames_dropped": drops,
        "frames_duplicated": dups,
        "dup_discards": snap.get(events.FRAME_DUPLICATE, 0),
        "gaps": snap.get(events.FRAME_GAP, 0),
        "corrupt": snap.get(events.FRAME_CORRUPT, 0),
        "dead_letters": snap.get(events.DEAD_LETTER, 0),
    }

    events.recorder.remove_listener(listener)
    events.recorder.disable()
    events.recorder.reset()
    for s in systems:
        try:
            s.terminate(timeout_s=5.0)
        except Exception:
            pass
    return result


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--churn", type=int, default=200)
    args = ap.parse_args()
    runs = [run_seed(1000 + i, args.churn) for i in range(args.seeds)]
    ok = [r for r in runs if r["converged"]]
    print(
        json.dumps(
            {
                "bench": "chaos recovery latency (tools/chaos_bench.py)",
                "config": {
                    k: v for k, v in BASE.items() if k.startswith("uigc.node")
                },
                "runs": runs,
                "converged": f"{len(ok)}/{len(runs)}",
                "detect_s_median": sorted(r["detect_s"] for r in runs)[
                    len(runs) // 2
                ],
                "converge_s_median": sorted(r["converge_s"] for r in runs)[
                    len(runs) // 2
                ],
            },
            indent=2,
        )
    )
    import os

    os._exit(0)


if __name__ == "__main__":
    main()
