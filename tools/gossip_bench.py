"""Cross-process delta-gossip throughput (runtime/node.py transport).

Two OS processes: a churn node continuously spawns and releases actors,
its collector folds the entries into DeltaGraphs and gossips them over
the real TCP link (reference: LocalGC.scala:159-165,191-196); the
measuring node counts delta frames, wire bytes, and shadow merges for a
fixed window.

Prints one JSON object; commit as ``BENCH_GOSSIP_r{N}.json``.

Usage: python tools/gossip_bench.py [--seconds 5]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 10,
    "uigc.crgc.num-nodes": 2,
}


def child(port: int, seconds: float) -> None:
    from uigc_tpu import AbstractBehavior, Behaviors, NoRefs
    from uigc_tpu.runtime.node import NodeFabric
    from uigc_tpu.runtime.system import ActorSystem

    class Tick(NoRefs):
        pass

    class Churner(AbstractBehavior):
        """Every tick: spawn a batch of children, share refs between
        them (cross-shadow edges for the delta), then release — a
        steady stream of created/released facts for the delta plane."""

        def __init__(self, context):
            super().__init__(context)
            self.n = 0

        def on_message(self, msg):
            ctx = self.context
            if isinstance(msg, Tick):
                kids = [
                    ctx.spawn(
                        Behaviors.setup(lambda c: Sink(c)), f"k{self.n}-{i}"
                    )
                    for i in range(8)
                ]
                self.n += 1
                refs = [ctx.create_ref(kids[i], kids[i - 1]) for i in range(8)]
                ctx.release(kids)
                ctx.release(refs)
            return self

    class Sink(AbstractBehavior):
        def on_message(self, msg):
            return self

    fabric = NodeFabric()
    system = ActorSystem(
        None, name="gossipChurn", config=dict(BASE), fabric=fabric
    )
    fabric.listen()
    fabric.connect("127.0.0.1", port)
    root = system.spawn_root(
        Behaviors.setup_root(lambda ctx: Churner(ctx)), "churner"
    )
    deadline = time.monotonic() + seconds + 2
    while time.monotonic() < deadline:
        root.tell(Tick())
        time.sleep(0.002)
    import os

    os._exit(0)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--child-port", type=int, default=0)
    args = ap.parse_args()
    if args.child_port:
        child(args.child_port, args.seconds)
        return

    from uigc_tpu.runtime.node import NodeFabric
    from uigc_tpu.runtime.system import ActorSystem

    fabric = NodeFabric()
    system = ActorSystem(
        None, name="gossipMeasure", config=dict(BASE), fabric=fabric
    )
    stats = {"deltas": 0, "delta_bytes": 0, "ringress": 0, "frames": 0}
    orig = fabric._on_frame

    def counting(addr, frame):
        stats["frames"] += 1
        if frame[0] == "delta":
            stats["deltas"] += 1
            stats["delta_bytes"] += len(frame[2])
        elif frame[0] == "ringress":
            stats["ringress"] += 1
        orig(addr, frame)

    fabric._on_frame = counting
    port = fabric.listen()

    proc = subprocess.Popen(
        [
            sys.executable,
            str(Path(__file__).resolve()),
            "--child-port",
            str(port),
            "--seconds",
            str(args.seconds),
        ]
    )
    # wait for the peer to join
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not fabric._conns:
        time.sleep(0.05)
    if not fabric._conns:
        raise RuntimeError("churn child never connected")

    baseline = dict(stats)
    t0 = time.perf_counter()
    time.sleep(args.seconds)
    dt = time.perf_counter() - t0
    deltas = stats["deltas"] - baseline["deltas"]
    dbytes = stats["delta_bytes"] - baseline["delta_bytes"]
    merged = system.engine.bookkeeper.shadow_graph.total_actors_seen

    proc.wait(timeout=30)
    print(
        json.dumps(
            {
                "bench": "cross-process delta gossip (tools/gossip_bench.py)",
                "seconds": round(dt, 2),
                "deltas_received": deltas,
                "deltas_per_sec": round(deltas / dt, 1),
                "delta_bytes_per_sec": round(dbytes / dt, 1),
                "remote_shadows_interned": int(merged),
                "frames_total": stats["frames"],
            }
        )
    )
    system.terminate()
    import os

    os._exit(0)


if __name__ == "__main__":
    main()
