"""Device-only per-wake cost: K decremental wakes chained in one program.

wake_bench.py measures the end-to-end wake, but on this host's axon
transport every value readback pays a ~70ms sync floor — any sub-100ms
per-wake cost drowns in it.  This probe pre-stages K wakes of churn as
device arrays (flag/recv scatters, layout mask scatters, suspect/fresh
words, xla-tier pair snapshots), scans the raw wake function over them
inside ONE jitted program, and times chain(K) against chain(2): the
difference divided by K-2 cancels the sync floor and the fixed
dispatch cost, leaving the true device per-wake time — the number the
<=10ms BASELINE target is judged against.

Per wake: half removals of live base pairs (masked in-layout + suspect
words), half fresh inserts (riding an xla tier whose cumulative per-wake
snapshot is pre-staged), plus a batch of flag/recv scatters (halts,
busy toggles, recv drains — the seed-churn suspects).  The final chain
state is cross-checked against the numpy oracle.

``--mode`` selects the repair fixpoint's propagation strategy
(uigc.crgc.trace-mode: push/pull/jump/auto).  Jump modes stage per-wake
jump-parent maintenance writes alongside the churn (minimum-fold on
insert, invalidate-on-remove — exactly the IncrementalPallasLayout
rules), so the chain exercises the production invariant that a pointer
never outlives the pair it was built from.  A stats replay (the same
staged wakes run unchained with the with_stats wake fn) reports the
per-wake repair sweep counts next to the chain figure, and ``--json``
dumps the whole result as a BENCH_WAKE-style artifact so the
sweep-count reduction is regression-tracked.

Usage: python tools/wake_chain_bench.py [--actors N] [--wakes 16]
       [--churn 20000] [--small] [--mode auto] [--json PATH]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--actors", type=int, default=None)
    ap.add_argument("--wakes", type=int, default=16)
    ap.add_argument("--churn", type=int, default=20_000)
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--no-oracle", action="store_true")
    ap.add_argument(
        "--mode", default="auto",
        choices=["auto", "push", "pull", "jump"],
        help="repair-fixpoint propagation strategy (uigc.crgc.trace-mode)",
    )
    ap.add_argument(
        "--no-stats", action="store_true",
        help="skip the per-wake sweep-count replay",
    )
    ap.add_argument("--json", default=None, help="dump the result JSON here")
    args = ap.parse_args()
    if args.wakes < 3:
        ap.error("--wakes must be >= 3 (chain(2) is the baseline)")

    import jax
    import jax.numpy as jnp

    from uigc_tpu.models import powerlaw_actor_graph
    from uigc_tpu.ops import pallas_decremental as pdec
    from uigc_tpu.ops import pallas_trace as pt
    from uigc_tpu.ops import trace as trace_ops
    from uigc_tpu.utils.platform import apply_platform_override, is_tpu_platform

    apply_platform_override()
    platform = jax.devices()[0].platform
    on_tpu = is_tpu_platform(platform)
    n = args.actors or (10_000_000 if on_tpu and not args.small else 1 << 16)
    K = args.wakes
    churn = args.churn if not args.small else min(args.churn, 512)

    rng = np.random.default_rng(11)
    graph = powerlaw_actor_graph(n, seed=0, garbage_fraction=0.5)
    flags0 = graph["flags"]
    recv0 = graph["recv_count"]

    # --- static base layout (no pow2 padding: fixed geometry) -------- #
    from uigc_tpu.ops.pallas_incremental import IncrementalPallasLayout

    psrc, pdst, kinds = IncrementalPallasLayout.pairs_from_graph(
        graph["edge_src"], graph["edge_dst"], graph["edge_weight"],
        graph["supervisor"],
    )
    t0 = time.perf_counter()
    prep = pt.prepare_pairs(psrc, pdst, n, want_slots=True)
    pack_s = time.perf_counter() - t0
    slot_ri = prep.pop("slot_ri")
    slot_col = prep.pop("slot_col")
    r_rows = prep["r_rows"]
    n_words_pad = r_rows * pt.LANE

    # the xla tier accumulates every insert across the chain
    cap = 1 << max(10, int(K * churn // 2 - 1).bit_length())
    xla = pt.xla_tier([], [], n, cap)
    specs = (pt.layout_spec(prep), pt.layout_spec(xla))
    mode = args.mode
    use_jump = mode in (pt.MODE_JUMP, pt.MODE_AUTO)
    wake_raw = pdec.get_wake_fn(
        n, specs, prep["n_super"], r_rows, prep["s_rows"], mode=mode
    ).raw

    # --- pre-stage K wakes of churn ---------------------------------- #
    d_half, i_half = churn // 2, churn // 2
    removable = np.nonzero(kinds == 0)[0]
    removed = np.zeros(psrc.size, bool)
    # membership via a sorted packed-key array: a Python set of ~30M
    # tuples would cost GBs of host RAM at the 10M-actor default
    base_sorted = np.sort((psrc << 32) | pdst)
    new_keys: set = set()
    ins_pairs: list = []

    f_churn = max(16, churn // 8)
    flag_slots = np.full((K, f_churn), n, np.int32)  # pad = dropped
    flag_vals = np.zeros((K, f_churn), np.uint8)
    recv_slots = np.full((K, f_churn), n, np.int32)
    recv_vals = np.zeros((K, f_churn), np.int64)
    mask_rows = np.full((K, d_half), prep["row_pos"].shape[0], np.int32)
    mask_cols = np.zeros((K, d_half), np.int32)
    del_words = np.zeros((K, r_rows, pt.LANE), np.uint32)
    fresh_words = np.zeros((K, r_rows, pt.LANE), np.uint32)
    xsrc = np.full((K, cap), n, np.int32)
    xdst = np.full((K, cap), n, np.int32)
    # per-wake jump-parent writes (dst -> final value after the wake's
    # removals invalidate + inserts min-fold); pad index n+2 is OOB of
    # the (n+1,) parent array, so .set(mode="drop") ignores it
    jp_now = pt.jump_parents(psrc, pdst, n) if use_jump else None
    jp0 = jp_now.copy() if use_jump else np.zeros(1, np.int32)
    jw_idx = np.full((K, churn), n + 2, np.int32)
    jw_val = np.zeros((K, churn), np.int32)

    def set_bits(words, ids):
        ids = np.asarray(ids, np.int64)
        if ids.size:
            flat = words.reshape(-1)
            np.bitwise_or.at(
                flat, ids >> 5, np.uint32(1) << (ids & 31).astype(np.uint32)
            )

    F = trace_ops
    flags_now = flags0.copy()
    recv_now = recv0.copy()
    n_ins_total = 0
    for k in range(K):
        # flag/recv churn: halts, busy toggles, recv drains/arrivals.
        # Staged per-wake as dicts so duplicate slots keep only the LAST
        # value — .at[].set with repeated indices applies in undefined
        # order on device, which would diverge from the host truth.
        f_updates: dict = {}
        r_updates: dict = {}
        for _ in range(f_churn):
            i = int(rng.integers(0, n))
            r = rng.random()
            if r < 0.3:
                flags_now[i] |= F.FLAG_HALTED
                f_updates[i] = flags_now[i]
            elif r < 0.7:
                flags_now[i] ^= F.FLAG_BUSY
                f_updates[i] = flags_now[i]
            else:
                recv_now[i] = 0 if recv_now[i] else 2
                r_updates[i] = recv_now[i]
        for j, (i, v) in enumerate(f_updates.items()):
            flag_slots[k, j] = i
            flag_vals[k, j] = v
        for j, (i, v) in enumerate(r_updates.items()):
            recv_slots[k, j] = i
            recv_vals[k, j] = v
        cand = rng.choice(removable, d_half, replace=False)
        cand = cand[~removed[cand]]
        removed[cand] = True
        mask_rows[k, : cand.size] = slot_ri[cand]
        mask_cols[k, : cand.size] = slot_col[cand]
        set_bits(del_words[k], pdst[cand])

        fresh = []
        while len(fresh) < i_half and n_ins_total + len(fresh) < cap:
            s_, d_ = int(rng.integers(0, n)), int(rng.integers(0, n))
            key = (s_ << 32) | d_
            if key in new_keys:
                continue
            pos = np.searchsorted(base_sorted, key)
            if pos < base_sorted.size and base_sorted[pos] == key:
                continue
            new_keys.add(key)
            fresh.append((s_, d_))
        ins_pairs.extend(fresh)
        n_ins_total = len(ins_pairs)
        # tier snapshot at wake k = every insert so far
        xsrc[k, :n_ins_total] = [p[0] for p in ins_pairs]
        xdst[k, :n_ins_total] = [p[1] for p in ins_pairs]
        set_bits(fresh_words[k], [p[1] for p in fresh])

        if use_jump:
            # Stage this wake's jump-parent maintenance (the
            # IncrementalPallasLayout rules): a removal invalidates the
            # pointer built from it, an insert folds in by minimum.
            aff = []
            rd, rs = pdst[cand], psrc[cand]
            hit = jp_now[rd] == rs
            jp_now[rd[hit]] = n
            aff.append(rd[hit])
            if fresh:
                fs = np.array([p[0] for p in fresh], np.int32)
                fd = np.array([p[1] for p in fresh], np.int64)
                prev = jp_now[fd].copy()
                np.minimum.at(jp_now, fd, fs)
                aff.append(fd[jp_now[fd] != prev])
            aff = np.unique(np.concatenate(aff))
            jw_idx[k, : aff.size] = aff
            jw_val[k, : aff.size] = jp_now[aff]

    dev = {
        "bmeta1": jax.device_put(prep["bmeta1"]),
        "bmeta2": jax.device_put(prep["bmeta2"]),
        "row_pos": jax.device_put(prep["row_pos"]),
        "emeta": jax.device_put(prep["emeta"]),
        "mask_rows": jax.device_put(mask_rows),
        "mask_cols": jax.device_put(mask_cols),
        "del_w": jax.device_put(del_words.view(np.int32)),
        "fresh_w": jax.device_put(fresh_words.view(np.int32)),
        "xsrc": jax.device_put(xsrc),
        "xdst": jax.device_put(xdst),
        "flags": jax.device_put(flags0),
        "recv": jax.device_put(recv0),
        "flag_slots": jax.device_put(flag_slots),
        "flag_vals": jax.device_put(flag_vals),
        "recv_slots": jax.device_put(recv_slots),
        "recv_vals": jax.device_put(recv_vals),
        "jp0": jax.device_put(jp0),
        "jw_idx": jax.device_put(jw_idx),
        "jw_val": jax.device_put(jw_val),
    }
    zeros_w = jnp.zeros((r_rows, pt.LANE), jnp.int32)

    @jax.jit
    def chained(k_hi, row_pos, emeta):
        state0 = (zeros_w,) * 5

        def body(k, carry):
            flags, recv, row_pos, emeta, jp, state = carry
            # in-chain churn: node-feature scatters + layout slot masks
            flags = flags.at[dev["flag_slots"][k]].set(
                dev["flag_vals"][k], mode="drop"
            )
            recv = recv.at[dev["recv_slots"][k]].set(
                dev["recv_vals"][k], mode="drop"
            )
            rows = dev["mask_rows"][k]
            cols = dev["mask_cols"][k]
            row_pos = row_pos.at[rows, cols].set(pt._PAD_ROW, mode="drop")
            emeta = emeta.at[rows, cols].set(0, mode="drop")
            if use_jump:
                # jump-parent maintenance lands BEFORE the wake, exactly
                # like the production _sync paths
                jp = jp.at[dev["jw_idx"][k]].set(
                    dev["jw_val"][k], mode="drop"
                )
                jarg = (jp,)
            else:
                jarg = ()
            state = wake_raw(
                flags,
                recv,
                dev["del_w"][k],
                dev["fresh_w"][k],
                *state,
                *jarg,
                dev["bmeta1"],
                dev["bmeta2"],
                row_pos,
                emeta,
                dev["xsrc"][k],
                dev["xdst"][k],
            )
            return (flags, recv, row_pos, emeta, jp, state)

        flags, recv, row_pos, emeta, _jp, state = jax.lax.fori_loop(
            0, k_hi, body,
            (dev["flags"], dev["recv"], row_pos, emeta, dev["jp0"], state0),
        )
        # data dependency on the final marks
        return jnp.sum(state[0]), state

    def run(k_hi):
        t0 = time.perf_counter()
        acc, state = chained(k_hi, dev["row_pos"], dev["emeta"])
        int(acc)  # readback sync
        return time.perf_counter() - t0, state

    log = lambda m: print(m, file=sys.stderr, flush=True)
    log(f"pack {pack_s:.1f}s; compiling chain (mode={mode})...")
    run(2)  # compile + warmup
    ts = []
    for _ in range(3):
        t_short, _ = run(2)
        t_long, state = run(K)
        ts.append((t_long - t_short) / (K - 2))
    per_wake_ms = statistics.median(ts) * 1e3

    result = {
        "bench": "wake_chain",
        "n_actors": n,
        "n_pairs": int(prep["n_pairs"]),
        "wakes_chained": K,
        "churn_per_wake": churn,
        "platform": platform,
        "trace_mode": mode,
        "host_pack_s": round(pack_s, 2),
        "device_per_wake_ms": round(per_wake_ms, 3),
        "target_p50_ms": 10.0,
        "vs_target": round(10.0 / max(per_wake_ms, 1e-9), 4),
    }

    if not args.no_stats:
        # Per-wake sweep counts: the same staged wakes replayed
        # UNCHAINED with the with_stats wake fn (device results feed
        # forward, churn applied host-side from the staged arrays), so
        # the sweep-count reduction is visible next to the chain figure.
        log("sweep-count replay...")
        wake_stats = pdec.get_wake_fn(
            n, specs, prep["n_super"], r_rows, prep["s_rows"], mode=mode,
            with_stats=True,
        )
        flags_k = flags0.copy()
        recv_k = recv0.copy()
        row_pos_h = prep["row_pos"].copy()
        emeta_h = prep["emeta"].copy()
        jp_h = jp0.copy()
        z = np.zeros((r_rows, pt.LANE), np.int32)
        state_r = tuple(jax.device_put(z) for _ in range(5))
        sweep_counts = []
        for k in range(K):
            fs, ok = flag_slots[k], flag_slots[k] < n
            flags_k[fs[ok]] = flag_vals[k][ok]
            rs, ok = recv_slots[k], recv_slots[k] < n
            recv_k[rs[ok]] = recv_vals[k][ok]
            mr, ok = mask_rows[k], mask_rows[k] < row_pos_h.shape[0]
            row_pos_h[mr[ok], mask_cols[k][ok]] = pt._PAD_ROW
            emeta_h[mr[ok], mask_cols[k][ok]] = 0
            if use_jump:
                jw, ok = jw_idx[k], jw_idx[k] <= n
                jp_h[jw[ok]] = jw_val[k][ok]
                jarg = (jp_h,)
            else:
                jarg = ()
            out = wake_stats(
                flags_k, recv_k,
                del_words[k].view(np.int32), fresh_words[k].view(np.int32),
                *state_r, *jarg,
                prep["bmeta1"], prep["bmeta2"], row_pos_h, emeta_h,
                xsrc[k], xdst[k],
            )
            state_r = out[:5]
            sweep_counts.append(int(out[5]["n_sweeps"]))
        result["sweep_counts"] = sweep_counts
        mean_sweeps = statistics.mean(sweep_counts)
        result["sweeps_mean"] = round(mean_sweeps, 2)
        result["sweeps_max"] = max(sweep_counts)
        result["device_per_sweep_ms"] = round(
            per_wake_ms / max(mean_sweeps, 1e-9), 3
        )

    if not args.no_oracle:
        # oracle on the final state: unpack marks from the chained state
        mark_w = np.asarray(state[0])
        shifts = np.arange(32, dtype=np.int64)
        bits = (mark_w.reshape(-1).astype(np.int64)[:, None] >> shifts) & 1
        got = bits.reshape(-1)[:n] > 0
        live = ~removed
        allsrc = np.concatenate([psrc[live], np.array([p[0] for p in ins_pairs], np.int64)])
        alldst = np.concatenate([pdst[live], np.array([p[1] for p in ins_pairs], np.int64)])
        expected = trace_ops.trace_marks_np(
            flags_now, recv_now, np.full(n, -1, np.int32),
            allsrc, alldst, np.ones(allsrc.size, np.int64),
        )
        result["oracle_ok"] = bool(np.array_equal(got, expected))

    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(result, fh, indent=2, sort_keys=True)
    if not args.no_oracle and not result["oracle_ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
