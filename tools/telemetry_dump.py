#!/usr/bin/env python
"""telemetry-dump: render uigc telemetry as Prometheus text or JSON.

Three sources, one output pipeline (build a metrics registry, render):

- ``--from-jsonl PATH``  replay a persisted JSONL event log
  (``uigc.telemetry.jsonl-path``) through the same event->metrics
  bridge a live system uses, so an offline dump and a live scrape of
  the same run agree;
- ``--demo``             run a tiny in-process workload with telemetry
  attached (spawn/churn/release under a fast collector) and dump what
  it produced — the zero-to-metrics smoke;
- ``--snapshot PATH``    pretty-print a recorder snapshot JSON file
  (``events.recorder.snapshot()`` saved by your driver) as-is.

Output: ``--format prom`` (default; Prometheus text exposition) or
``--format json`` (the registry snapshot).  One document to stdout.

``--series NAME`` switches to the telemetry time plane: render one
stored series (every labelset fan-out) as an ASCII sparkline + stats,
from a live ``/timeseries`` endpoint (``--url``) or a JSONL replay
(``--from-jsonl``) — the renderers are shared with ``tools/uigc_top.py``.

``--device`` renders the device-plane observatory
(``uigc.telemetry.device``): from a live ``/device`` endpoint
(``--url``) or by replaying the event-fed planes (compile cache, host
transfers, donation audit) out of a JSONL sink — the renderers are
``tools/device_report.py``'s.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _registry(node: str):
    from uigc_tpu.telemetry.metrics import EventMetricsBridge, MetricsRegistry

    registry = MetricsRegistry(const_labels={"node": node})
    return registry, EventMetricsBridge(registry)


def dump_from_jsonl(path: str, fmt: str) -> int:
    from uigc_tpu.telemetry.exporter import prometheus_text, replay_jsonl

    registry, bridge = _registry(node=f"replay:{Path(path).name}")
    n = 0
    for name, fields in replay_jsonl(path):
        bridge(name, fields)
        n += 1
    if n == 0:
        print(f"telemetry-dump: no events in {path!r}", file=sys.stderr)
        return 1
    if fmt == "json":
        print(json.dumps(registry.snapshot(), indent=2, sort_keys=True, default=repr))
    else:
        sys.stdout.write(prometheus_text(registry))
    return 0


def dump_demo(fmt: str) -> int:
    from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, NoRefs
    from uigc_tpu.telemetry.exporter import prometheus_text

    class Ping(NoRefs):
        pass

    class Worker(AbstractBehavior):
        def on_message(self, msg):
            return self

    class Root(AbstractBehavior):
        def __init__(self, context):
            super().__init__(context)
            self.workers = [
                context.spawn(Behaviors.setup(Worker), f"w{i}") for i in range(8)
            ]

        def on_message(self, msg):
            ctx = self.context
            if isinstance(msg, Ping) and self.workers:
                for worker in self.workers:
                    worker.tell(Ping(), ctx)
            elif self.workers:
                ctx.release(*self.workers)
                self.workers = []
            return self

    kit = ActorTestKit(
        config={
            "uigc.crgc.wakeup-interval": 10,
            "uigc.telemetry.metrics": True,
            "uigc.telemetry.wake-profile": True,
        },
        name="telemetry-demo",
    )
    try:
        root = kit.spawn(Behaviors.setup_root(Root), "root")
        for _ in range(50):
            root.tell(Ping())
        time.sleep(0.3)
        root.tell(object())  # release branch
        time.sleep(0.5)
        telemetry = kit.system.telemetry
        if fmt == "json":
            doc = {
                "metrics": telemetry.registry.snapshot(),
                "wake_profile": telemetry.profiler.to_json(),
            }
            print(json.dumps(doc, indent=2, sort_keys=True, default=repr))
        else:
            sys.stdout.write(prometheus_text(telemetry.registry))
    finally:
        kit.shutdown()
    return 0


def dump_snapshot(path: str, fmt: str) -> int:
    with open(path) as fh:
        snap = json.load(fh)
    if fmt == "json":
        print(json.dumps(snap, indent=2, sort_keys=True))
        return 0
    # Render a recorder snapshot as gauges/counters: counts are
    # monotone (counter-like), sums and duration stats become gauges.
    lines = []
    for name, count in sorted(snap.get("counts", {}).items()):
        metric = "uigc_event_total{event=\"%s\"}" % name
        lines.append(f"{metric} {count}")
    for name, value in sorted(snap.get("sums", {}).items()):
        lines.append('uigc_event_sum{field="%s"} %s' % (name, value))
    for name, stat in sorted(snap.get("durations", {}).items()):
        for key in ("n", "total_s", "max_s"):
            lines.append(
                'uigc_event_duration_%s{event="%s"} %s' % (key, name, stat[key])
            )
    sys.stdout.write("\n".join(lines) + "\n")
    return 0


def dump_inspect(path, actor, fmt) -> int:
    """Pretty-print a liveness-inspector snapshot (and optionally one
    why-live retaining path): from a dumped JSON file when ``path`` is
    given, else from a live in-process demo system — the rendering is
    shared with tools/graph_inspect.py."""
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    import graph_inspect

    from uigc_tpu.telemetry.inspect import why_live

    if path:
        snap = graph_inspect.load_snapshot(path)
        result = why_live(snap, actor) if actor else None
    else:
        demo = graph_inspect.DemoSystem()
        try:
            snap = demo.inspector.snapshot()
            result = demo.inspector.why_live(actor) if actor else None
        finally:
            demo.shutdown()
    if fmt == "json":
        doc = {"snapshot": snap}
        if result is not None:
            doc["why_live"] = result
        print(json.dumps(doc, indent=2, sort_keys=True, default=repr))
    else:
        print(graph_inspect.render_snapshot(snap))
        if result is not None:
            print(graph_inspect.render_why_live(result))
    return 0


def dump_series(name, url, jsonl, fmt) -> int:
    """Render one stored time-plane series (every labelset fan-out) as
    an ASCII sparkline + stats, from a live ``/timeseries`` endpoint or
    a JSONL replay — the renderers are tools/uigc_top.py's."""
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    import uigc_top

    if url:
        try:
            tsdoc, _alerts, _metrics = uigc_top.fetch_live(
                url.rstrip("/"), window=1e9
            )
        except Exception as exc:
            print(f"telemetry-dump: {exc}", file=sys.stderr)
            return 1
    else:
        try:
            tsdoc, _alerts, _metrics = uigc_top.replay_model(jsonl)
        except (FileNotFoundError, OSError) as exc:
            print(f"telemetry-dump: {exc}", file=sys.stderr)
            return 1
    matching = [s for s in tsdoc.get("series", []) if s.get("name") == name]
    if not matching:
        known = sorted({s.get("name") for s in tsdoc.get("series", [])})
        print(
            f"telemetry-dump: no series {name!r}; known: {', '.join(known)}",
            file=sys.stderr,
        )
        return 1
    if fmt == "json":
        print(json.dumps(
            {"name": name, "series": matching},
            indent=2, sort_keys=True, default=repr,
        ))
        return 0
    mode = "rate" if name.endswith("_total") else "mean"
    print(f"{name}  ({len(matching)} labelset(s), mode={mode})")
    for series in matching:
        labels = series.get("labels") or {}
        label = (
            ",".join(f"{k}={v}" for k, v in sorted(labels.items())) or "(all)"
        )
        points = uigc_top.series_points(series, mode)
        print("  " + uigc_top.render_series(label[:16], points, width=48))
    return 0


def dump_device(url, jsonl, fmt) -> int:
    """Render the device observatory: live ``/device`` or the event-fed
    planes replayed from a JSONL sink (tools/device_report.py
    renderers)."""
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    import device_report
    import uigc_top

    if url:
        try:
            doc = device_report.fetch_doc(url.rstrip("/"))
        except Exception as exc:
            print(
                f"telemetry-dump: no /device at {url} "
                f"(uigc.telemetry.device off?): {exc}",
                file=sys.stderr,
            )
            return 1
    else:
        doc = uigc_top.replay_device(jsonl)
        if doc is None:
            print(
                f"telemetry-dump: no replayable events in {jsonl!r}",
                file=sys.stderr,
            )
            return 1
    if fmt == "json":
        print(json.dumps(doc, indent=2, sort_keys=True, default=repr))
        return 0
    print(
        device_report.render_device_doc(
            doc, device_report.committed_device_figures()
        )
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="telemetry-dump", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "--series",
        metavar="NAME",
        help="render one time-plane series (sparkline + stats) from "
        "--url or --from-jsonl (tools/uigc_top.py renderers)",
    )
    parser.add_argument(
        "--device",
        action="store_true",
        help="render the device-plane observatory from --url (/device) "
        "or --from-jsonl (tools/device_report.py renderers)",
    )
    parser.add_argument(
        "--url",
        metavar="URL",
        help="live metrics-HTTP base URL for --series",
    )
    source = parser.add_mutually_exclusive_group()
    source.add_argument("--from-jsonl", metavar="PATH", help="replay a JSONL event log")
    source.add_argument(
        "--demo", action="store_true", help="run a tiny workload and dump its metrics"
    )
    source.add_argument(
        "--snapshot", metavar="PATH", help="render a saved recorder snapshot JSON"
    )
    source.add_argument(
        "--inspect",
        nargs="?",
        const="",
        metavar="SNAPJSON",
        default=None,
        help="pretty-print a liveness snapshot (from SNAPJSON when "
        "given, else from a live demo system); combine with --actor "
        "for a why-live path (tools/graph_inspect.py)",
    )
    parser.add_argument(
        "--actor", metavar="NAME", help="actor to explain with --inspect"
    )
    parser.add_argument(
        "--format",
        choices=("prom", "json"),
        default="prom",
        help="output format (default: prom)",
    )
    args = parser.parse_args(argv)
    if args.device:
        if not args.url and not args.from_jsonl:
            parser.error("--device needs --url or --from-jsonl")
        return dump_device(args.url, args.from_jsonl, args.format)
    if args.series:
        if not args.url and not args.from_jsonl:
            parser.error("--series needs --url or --from-jsonl")
        return dump_series(args.series, args.url, args.from_jsonl, args.format)
    if args.inspect is not None:
        return dump_inspect(args.inspect, args.actor, args.format)
    if args.from_jsonl:
        return dump_from_jsonl(args.from_jsonl, args.format)
    if args.snapshot:
        return dump_snapshot(args.snapshot, args.format)
    if args.demo:
        return dump_demo(args.format)
    parser.error(
        "one of --from-jsonl / --demo / --snapshot / --inspect is required"
    )
    return 2


if __name__ == "__main__":
    sys.exit(main())
