#!/usr/bin/env python
"""uigc-check: whole-repo cross-plane static analysis — CLI shim.

The analyzer lives in ``uigc_tpu/analysis/check/`` (shared single
parse; lint + surface-registry + lock-graph + trace-purity passes);
this script only puts the repo root on ``sys.path`` and dispatches.

    python tools/uigc_check.py --strict uigc_tpu/ tools/

See ``uigc_tpu/analysis/check/cli.py`` for flags, GUIDE.md
("Correctness tooling") for the two-layer story, and PROFILING.md
("Reading uigc_check") for a worked finding.
"""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from uigc_tpu.analysis.check import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
