#!/usr/bin/env python
"""uigc-top: live ops dashboard for a uigc node (or a cluster).

Renders the telemetry time plane (``uigc.telemetry.timeseries``) as a
terminal dashboard: sparklines per key series, actor/entity/shard
counts, firing anomaly/SLO alerts, per-peer link health (phi,
writer-queue depth), and — when the node serves ``/device``
(``uigc.telemetry.device``) — a device-observatory panel (ledger
bytes, per-wake device time, compile hit/miss, transfer and donation
tallies; dashes on nodes that predate the observatory).  Two sources:

- ``--url http://127.0.0.1:PORT``  poll a live node's metrics HTTP
  server (``/timeseries`` + ``/alerts`` + ``/metrics.json``); add
  ``--merged`` to pull the cluster-wide view over the ``tsq``/``tsr``
  fabric frames (surviving peers merge, dead ones show under
  ``missing``).
- ``--from-jsonl PATH``  replay a persisted (possibly rotated) JSONL
  event sink offline: the same event->metrics bridge a live node runs
  rebuilds the registry, a synthetic-clock sampler folds it into a
  store, and the built-in alert rules re-evaluate — one static frame
  of what the run looked like.

Display: full-screen curses when stdout is a TTY (q quits), else (or
with ``--plain``) one frame per poll to stdout; ``--once`` prints a
single frame and exits.  The renderers (:func:`sparkline`,
:func:`render_dashboard`, :func:`series_points`) are shared with
``tools/telemetry_dump.py --series``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

SPARK_CHARS = "▁▂▃▄▅▆▇█"

#: (series, label, mode) rows of the dashboard body.  ``mode``:
#: value series render the bucket aggregate, ``rate`` differentiates a
#: sampled counter into per-second deltas.
KEY_SERIES: Tuple[Tuple[str, str, str], ...] = (
    ("uigc_wake_wall_seconds", "wake wall s", "mean"),
    ("uigc_wake_device_seconds", "wake device s", "mean"),
    ("uigc_live_actors", "live actors", "last"),
    # the bridge-fed twin (TRACING events): the row an offline JSONL
    # replay can still show, where callback gauges never existed
    ("uigc_gc_live_actors", "gc live actors", "last"),
    ("uigc_mailbox_depth", "mailbox depth", "last"),
    ("uigc_entries_flushed_total", "entries/s", "rate"),
    ("uigc_gc_garbage_total", "garbage/s", "rate"),
    ("uigc_frame_gaps_total", "frame gaps/s", "rate"),
    ("uigc_frame_duplicates_total", "frame dups/s", "rate"),
    ("uigc_writer_queue_depth", "writer queue", "max"),
    ("uigc_send_matrix_pairs", "send pairs", "last"),
    ("uigc_leak_suspects_total", "leak suspects", "last"),
    ("uigc_fence_rejected_total", "fence rejects/s", "rate"),
    ("uigc_dist_marks_exchanged_total", "dist marks/s", "rate"),
)

#: header gauges pulled from /metrics.json: (metric, short label)
HEADER_GAUGES: Tuple[Tuple[str, str], ...] = (
    ("uigc_live_actors", "actors"),
    ("uigc_shadow_graph_size", "shadows"),
    ("uigc_shard_table_size", "shards"),
    ("uigc_shard_entities_active", "entities"),
    ("uigc_shard_entities_passivated", "passivated"),
    ("uigc_dead_letters", "dead-letters"),
)


# ------------------------------------------------------------------- #
# Renderers (shared with telemetry_dump --series)
# ------------------------------------------------------------------- #


def fmt_si(value: Optional[float]) -> str:
    """Compact SI rendering: 1234567 -> '1.2M', 0.00042 -> '420µ'."""
    if value is None:
        return "-"
    v = float(value)
    if v == 0:
        return "0"
    sign = "-" if v < 0 else ""
    v = abs(v)
    for bound, suffix, div in (
        (1e9, "G", 1e9), (1e6, "M", 1e6), (1e3, "k", 1e3),
    ):
        if v >= bound:
            return f"{sign}{v / div:.1f}{suffix}"
    if v >= 1:
        return f"{sign}{v:.3g}"
    for bound, suffix, div in ((1e-3, "m", 1e-3), (1e-6, "µ", 1e-6)):
        if v >= bound:
            return f"{sign}{v / div:.3g}{suffix}"
    return f"{sign}{v:.2e}"


def sparkline(values: List[Optional[float]], width: int = 48) -> str:
    """One-line block-character sparkline; None gaps render as spaces.
    Scaled to the window's own min/max (the stats column carries the
    absolute numbers)."""
    values = list(values)[-width:]
    present = [v for v in values if v is not None]
    if not present:
        return "·" * 4
    lo, hi = min(present), max(present)
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(SPARK_CHARS[0] if hi <= 0 else SPARK_CHARS[3])
        else:
            idx = int((v - lo) / span * (len(SPARK_CHARS) - 1))
            out.append(SPARK_CHARS[idx])
    return "".join(out)


def series_points(
    series_doc: Dict[str, Any], mode: str = "mean"
) -> List[Tuple[float, float]]:
    """(t, value) points from one ``/timeseries`` series entry (its
    finest tier) or a ``range()`` result.  ``rate`` differentiates the
    per-bucket ``last`` samples into per-second slopes."""
    if "buckets" in series_doc and "tiers" not in series_doc:
        res = float(series_doc.get("resolution", 1.0)) or 1.0
        rows = [
            [b["t"] / res, b["count"], b["sum"], b["min"], b["max"], b["last"]]
            for b in series_doc["buckets"]
        ]
    else:
        tiers = series_doc.get("tiers") or []
        if not tiers:
            return []
        tier = tiers[0]
        res = float(tier.get("res", 1.0)) or 1.0
        rows = tier.get("buckets", [])
    points: List[Tuple[float, float]] = []
    prev: Optional[Tuple[float, float]] = None
    for row in rows:
        try:
            idx, count, total, vmin, vmax, last = row
        except (TypeError, ValueError):
            continue
        t = idx * res
        if mode == "rate":
            if prev is not None and t > prev[0]:
                points.append((t, max(0.0, (last - prev[1]) / (t - prev[0]))))
            prev = (t, last)
        elif mode == "max":
            points.append((t, vmax))
        elif mode == "last":
            points.append((t, last))
        else:
            points.append((t, total / count if count else 0.0))
    return points


def render_series(
    label: str, points: List[Tuple[float, float]], width: int = 48
) -> str:
    """One dashboard row: label, sparkline, min/mean/max/last stats."""
    values = [v for _t, v in points]
    spark = sparkline(values, width=width)
    if values:
        stats = (
            f"min {fmt_si(min(values)):>7}  mean "
            f"{fmt_si(sum(values) / len(values)):>7}  "
            f"max {fmt_si(max(values)):>7}  last {fmt_si(values[-1]):>7}"
        )
    else:
        stats = "(no data)"
    return f"{label:<16} {spark:<{width}} {stats}"


def _labels_str(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _find_series(
    doc: Dict[str, Any], name: str
) -> List[Dict[str, Any]]:
    return [s for s in doc.get("series", []) if s.get("name") == name]


def _merged_as_series(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Adapt a merged (cluster) document's rollup entries to the
    per-node series shape the renderers consume."""
    out = []
    for entry in doc.get("cluster", []):
        out.append(
            {
                "name": entry.get("name"),
                "labels": entry.get("labels", {}),
                "tiers": [
                    {"res": entry.get("res", 1.0), "buckets": entry.get("buckets", [])}
                ],
            }
        )
    return out


def _gauge_value(metrics: Dict[str, Any], name: str) -> Optional[float]:
    entry = metrics.get(name)
    if not entry:
        return None
    total = None
    for sample in entry.get("samples", []):
        if sample.get("suffix"):
            continue
        total = (total or 0.0) + float(sample.get("value", 0.0))
    return total


def render_device_panel(device: Optional[Dict[str, Any]]) -> List[str]:
    """The device-observatory rows.  A node that predates the /device
    route (or runs with the observatory off) renders dashes — the panel
    must degrade, never crash, on an old or un-instrumented peer."""
    if not isinstance(device, dict):
        return ["device: -  (observatory off, or node predates /device)"]
    try:
        ledger = device.get("ledger") or {}
        compile_doc = device.get("compile") or {}
        transfers = device.get("transfers") or {}
        donation = device.get("donation") or {}
        wakes = [
            r for r in device.get("recent_wakes") or [] if r.get("device_s")
        ]
        if wakes:
            mean_ms = sum(r["device_s"] for r in wakes) / len(wakes) * 1e3
            wake_cell = f"{mean_ms:.2f}ms/wake"
        else:
            wake_cell = "-"
        sweeps = [int(r["n_sweeps"]) for r in wakes if r.get("n_sweeps")]
        sweeps_cell = (
            f"{sum(sweeps) / len(sweeps):.1f} sweeps" if sweeps else "-"
        )
        lines = [
            "device: ledger "
            + fmt_si(ledger.get("total_bytes"))
            + "B ("
            + fmt_si(ledger.get("device_bytes"))
            + "B on-device) · "
            + wake_cell
            + " · "
            + sweeps_cell
            + f" · compile {fmt_si(compile_doc.get('hits_total'))}h/"
            + f"{fmt_si(compile_doc.get('misses_total'))}m"
            + f" · transfers {fmt_si(transfers.get('total_count'))}"
            + f" · donation copies {fmt_si(donation.get('copies_total'))}"
        ]
        families = sorted(
            (ledger.get("families") or {}).items(),
            key=lambda kv: -(kv[1].get("host", 0) + kv[1].get("device", 0)),
        )[:4]
        cells = [
            f"{fam} {fmt_si(t.get('host', 0) + t.get('device', 0))}B"
            for fam, t in families
            if t.get("host", 0) + t.get("device", 0)
        ]
        if cells:
            lines.append("  " + "  ".join(cells))
        return lines
    except Exception:
        return ["device: -  (unreadable /device document)"]


def render_dashboard(
    tsdoc: Dict[str, Any],
    alerts: Optional[Dict[str, Any]] = None,
    metrics: Optional[Dict[str, Any]] = None,
    width: int = 48,
    source: str = "",
    device: Optional[Dict[str, Any]] = None,
) -> str:
    """The full dashboard frame as plain text."""
    lines: List[str] = []
    merged = bool(tsdoc.get("merged"))
    series_list = (
        _merged_as_series(tsdoc) if merged else tsdoc.get("series", [])
    )
    node = tsdoc.get("node", "cluster" if merged else "?")
    stamp = time.strftime("%H:%M:%S", time.localtime(tsdoc.get("t", time.time())))
    title = f"uigc-top · {node} · {stamp}"
    if source:
        title += f" · {source}"
    lines.append(title)
    if merged:
        nodes = sorted(tsdoc.get("nodes", {}))
        missing = tsdoc.get("missing_nodes", [])
        lines.append(
            f"cluster: {len(nodes)} node(s) merged"
            + (f" · missing: {', '.join(missing)}" if missing else "")
        )
    if metrics:
        cells = []
        for name, label in HEADER_GAUGES:
            value = _gauge_value(metrics, name)
            if value is not None:
                cells.append(f"{label} {fmt_si(value)}")
        if cells:
            lines.append("  ".join(cells))
    lines.append("-" * (width + 60))
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for s in series_list:
        by_name.setdefault(s.get("name", "?"), []).append(s)
    for name, label, mode in KEY_SERIES:
        fans = by_name.get(name)
        if not fans:
            continue
        if len(fans) == 1:
            lines.append(
                render_series(label, series_points(fans[0], mode), width)
            )
        else:
            lines.append(f"{label}:")
            for fan in fans:
                sub = _labels_str(fan.get("labels", {})) or "(all)"
                lines.append(
                    "  "
                    + render_series(sub[:14], series_points(fan, mode), width)
                )
    # Per-peer link health: phi + writer queue keyed by peer label.
    peers: Dict[str, Dict[str, float]] = {}
    for s in _find_series({"series": series_list}, "uigc_link_phi"):
        peer = s.get("labels", {}).get("peer")
        pts = series_points(s, "last")
        if peer and pts:
            peers.setdefault(peer, {})["phi"] = pts[-1][1]
    for s in _find_series({"series": series_list}, "uigc_writer_queue_depth"):
        peer = s.get("labels", {}).get("peer")
        pts = series_points(s, "max")
        if peer and pts:
            peers.setdefault(peer, {})["queue"] = pts[-1][1]
    if peers:
        lines.append("")
        lines.append("links:")
        for peer, health in sorted(peers.items()):
            phi = health.get("phi")
            state = "ok" if phi is None or phi < 1.0 else (
                "suspect" if phi < 4.0 else "CRITICAL"
            )
            lines.append(
                f"  {peer:<28} phi {fmt_si(phi):>7}  "
                f"queue {fmt_si(health.get('queue')):>7}  [{state}]"
            )
    def metric_row(title, pairs, show_at_zero=()):
        """One 'plane' row: each metric's last sample summed over its
        labelsets.  Metrics in ``show_at_zero`` render even at 0 (an
        idle gauge is informative; an untouched counter is noise)."""
        cells = []
        for metric, label in pairs:
            total = 0.0
            seen_any = False
            for s in _find_series({"series": series_list}, metric):
                pts = series_points(s, "last")
                if pts:
                    seen_any = True
                    total += pts[-1][1]
            if seen_any and (total > 0 or metric in show_at_zero):
                cells.append(f"{label} {fmt_si(total)}")
        if cells:
            lines.append("")
            lines.append(title + ": " + "  ".join(cells))

    # Partition-tolerance counters (cluster/membership.py): nonzero
    # means the split-brain plane acted (or is refusing stale work).
    metric_row(
        "partition plane",
        (
            ("uigc_cluster_partitions_total", "partitions"),
            ("uigc_sbr_downed_total", "sbr-downed"),
            ("uigc_fence_rejected_total", "fence-rejected"),
            ("uigc_membership_disagreements_total", "view-conflicts"),
        ),
    )
    # Distributed-collector plane (engines/crgc/distributed.py): the
    # cross-node trace protocol's surface — boundary edges shown even
    # at zero so an idle partitioned node is visible.
    metric_row(
        "distributed collector",
        (
            ("uigc_dist_boundary_edges", "boundary-edges"),
            ("uigc_dist_marks_exchanged_total", "marks"),
            ("uigc_dist_mark_bytes_total", "mark-bytes"),
            ("uigc_dist_wave_rounds_total", "rounds"),
            ("uigc_dist_refolds_total", "refolds"),
            ("uigc_dist_mirror_evictions_total", "mirror-evicts"),
        ),
        show_at_zero=("uigc_dist_boundary_edges",),
    )
    # Ingress-gateway plane (uigc_tpu/gateway): the front door — live
    # connections and egress depth shown even at zero so an attached
    # but idle gateway is visible.
    metric_row(
        "ingress gateway",
        (
            ("uigc_gateway_connections", "conns"),
            ("uigc_gateway_tenant_msgs_total", "msgs"),
            ("uigc_gateway_shed_total", "shed"),
            ("uigc_gateway_egress_queue_depth", "egress-depth"),
        ),
        show_at_zero=(
            "uigc_gateway_connections",
            "uigc_gateway_egress_queue_depth",
        ),
    )
    lines.append("")
    lines.extend(render_device_panel(device))
    firing = (alerts or {}).get("firing", [])
    lines.append("")
    if firing:
        lines.append(f"ALERTS ({len(firing)} firing):")
        for alert in firing:
            labels = _labels_str(alert.get("labels", {}))
            lines.append(
                f"  [{alert.get('severity', '?'):>8}] {alert.get('rule')}"
                f"{labels}  value={fmt_si(alert.get('value'))} "
                f"threshold={fmt_si(alert.get('threshold'))}"
            )
    else:
        lines.append("alerts: none firing")
    return "\n".join(lines)


# ------------------------------------------------------------------- #
# Sources
# ------------------------------------------------------------------- #


def fetch_live(
    base: str, merged: bool = False, window: float = 180.0
) -> Tuple[Dict[str, Any], Optional[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """(timeseries doc, alerts doc, metrics.json) from a live node."""

    def get(path: str) -> Optional[Dict[str, Any]]:
        try:
            with urllib.request.urlopen(base + path, timeout=5) as rsp:
                return json.loads(rsp.read())
        except Exception:
            return None

    ts_path = f"/timeseries?window={window:g}"
    if merged:
        ts_path += "&merged=1"
    tsdoc = get(ts_path)
    if tsdoc is None:
        raise ConnectionError(f"no /timeseries at {base} (timeseries off?)")
    return tsdoc, get("/alerts"), get("/metrics.json")


def fetch_device(base: str) -> Optional[Dict[str, Any]]:
    """The /device observatory doc, or None on a node that predates it
    or runs with ``uigc.telemetry.device`` off — the device panel
    renders dashes for None, never raises."""
    try:
        with urllib.request.urlopen(base + "/device", timeout=5) as rsp:
            return json.loads(rsp.read())
    except Exception:
        return None


def replay_device(path: str) -> Optional[Dict[str, Any]]:
    """Rebuild the event-fed observatory planes (compile cache, host
    transfers, donation audit) from a persisted JSONL sink — the memory
    ledger needs a live graph and stays empty offline."""
    try:
        from uigc_tpu.telemetry.device import DeviceObservatory
        from uigc_tpu.telemetry.exporter import replay_jsonl

        # Unscoped (node="") so the origin filter accepts the sink's
        # events — every persisted line carries the live node's origin
        # tag, which a "replay:<file>" node name would reject wholesale.
        obs = DeviceObservatory(node="")
        try:
            for name, fields in replay_jsonl(path):
                obs(name, fields)
            obs.node = f"replay:{Path(path).name}"  # display only
            return obs.to_doc()
        finally:
            obs.close()
    except Exception:
        return None


def replay_model(
    path: str, stride: int = 200
) -> Tuple[Dict[str, Any], Dict[str, Any], Dict[str, Any]]:
    """Rebuild (timeseries doc, alerts doc, metrics.json) offline from
    a JSONL event sink: the live event->metrics bridge refills a
    registry, and a synthetic 1s-per-``stride``-events clock samples it
    into a store while the built-in rules re-evaluate."""
    from uigc_tpu.config import Config
    from uigc_tpu.telemetry.alerts import AlertEngine, builtin_rules
    from uigc_tpu.telemetry.exporter import replay_jsonl
    from uigc_tpu.telemetry.metrics import EventMetricsBridge, MetricsRegistry
    from uigc_tpu.telemetry.timeseries import MetricsSampler, TimeSeriesStore

    node = f"replay:{Path(path).name}"
    registry = MetricsRegistry()
    bridge = EventMetricsBridge(registry)
    clock_t = [time.time() - 3600.0]
    store = TimeSeriesStore(node=node, clock=lambda: clock_t[0])
    engine = AlertEngine(store, node=node)
    engine.add_rules(builtin_rules(Config()))
    sampler = MetricsSampler(
        store, registry=registry, alerts=engine, clock=lambda: clock_t[0]
    )
    n = 0
    for name, fields in replay_jsonl(path):
        bridge(name, fields)
        n += 1
        if n % stride == 0:
            sampler.sample_once(clock_t[0])
            clock_t[0] += 1.0
    if n == 0:
        raise FileNotFoundError(f"no events in {path!r}")
    sampler.sample_once(clock_t[0])
    return (
        store.to_doc(),
        engine.to_doc(),
        registry.snapshot(),
    )


# ------------------------------------------------------------------- #
# Main loop
# ------------------------------------------------------------------- #


def _curses_loop(args) -> int:
    import curses

    def body(screen) -> None:
        curses.curs_set(0)
        screen.nodelay(True)
        while True:
            # A transient fetch failure (node saturated, mid-restart)
            # renders as a stale-data notice — a top-style tool keeps
            # polling through exactly the windows where the system is
            # most interesting.
            try:
                tsdoc, alerts, metrics = fetch_live(
                    args.url, merged=args.merged, window=args.window
                )
                frame = render_dashboard(
                    tsdoc, alerts, metrics, width=args.width, source=args.url,
                    device=fetch_device(args.url),
                )
            except Exception as exc:
                frame = f"uigc-top · {args.url}\n\nno data: {exc}\nretrying…"
            screen.erase()
            rows, cols = screen.getmaxyx()
            for i, line in enumerate(frame.splitlines()[: rows - 1]):
                try:
                    screen.addnstr(i, 0, line, cols - 1)
                except curses.error:
                    pass
            screen.refresh()
            deadline = time.monotonic() + args.interval
            while time.monotonic() < deadline:
                ch = screen.getch()
                if ch in (ord("q"), ord("Q")):
                    return
                time.sleep(0.05)

    curses.wrapper(body)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="uigc-top", description=__doc__.splitlines()[0]
    )
    source = parser.add_mutually_exclusive_group(required=True)
    source.add_argument(
        "--url", metavar="URL", help="live node base URL (http://host:port)"
    )
    source.add_argument(
        "--from-jsonl", metavar="PATH", help="replay a JSONL event sink"
    )
    parser.add_argument(
        "--merged", action="store_true",
        help="pull the cluster-wide merged view (tsq/tsr) from the node",
    )
    parser.add_argument(
        "--interval", type=float, default=1.0, help="poll interval seconds"
    )
    parser.add_argument(
        "--window", type=float, default=180.0, help="history window seconds"
    )
    parser.add_argument("--width", type=int, default=48, help="sparkline width")
    parser.add_argument(
        "--once", action="store_true", help="print one frame and exit"
    )
    parser.add_argument(
        "--plain", action="store_true",
        help="never use curses; print frames to stdout",
    )
    args = parser.parse_args(argv)

    if args.from_jsonl:
        try:
            tsdoc, alerts, metrics = replay_model(args.from_jsonl)
        except (FileNotFoundError, OSError) as exc:
            print(f"uigc-top: {exc}", file=sys.stderr)
            return 1
        print(
            render_dashboard(
                tsdoc, alerts, metrics, width=args.width,
                source=f"jsonl:{args.from_jsonl}",
                device=replay_device(args.from_jsonl),
            )
        )
        return 0

    base = args.url.rstrip("/")
    args.url = base
    if args.once or args.plain or not sys.stdout.isatty():
        while True:
            try:
                tsdoc, alerts, metrics = fetch_live(
                    base, merged=args.merged, window=args.window
                )
            except Exception as exc:
                print(f"uigc-top: {exc}", file=sys.stderr)
                if args.once:
                    return 1
                # transient: keep polling (see the curses loop's note)
                time.sleep(args.interval)
                continue
            print(
                render_dashboard(
                    tsdoc, alerts, metrics, width=args.width, source=base,
                    device=fetch_device(base),
                )
            )
            if args.once:
                return 0
            sys.stdout.flush()
            time.sleep(args.interval)
    try:
        return _curses_loop(args)
    except Exception as exc:
        print(f"uigc-top: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
