#!/usr/bin/env python
"""graph-inspect: query the liveness inspector — why-live retaining
paths, shadow-graph snapshots, retained-set diffs, and a self-check.

The heap-dump/retained-path tool of the collector (GUIDE.md "Debugging
liveness").  Sources:

- ``--url http://127.0.0.1:PORT``  a live system's telemetry HTTP
  server (``uigc.telemetry.http-port`` + ``uigc.telemetry.inspect``);
  hits ``/snapshot`` (``--merged`` = the cluster-wide graph via the
  "snap" NodeFabric exchange) and ``/inspect?actor=...``;
- ``--from FILE``  a dumped snapshot JSON (flight-recorder dump or a
  previous ``graph_inspect snapshot -o``);
- ``--demo``  a small in-process system (chain of retained actors plus
  one deliberately leaked pin) — the zero-to-inspection smoke.

Subcommands:

  snapshot   dump one (optionally merged) snapshot as JSON
  why-live   print a pseudoroot→actor retaining path with per-hop
             provenance (created edge / supervisor pointer)
  diff       retained-set diff of two snapshot files
  selfcheck  drive the demo system, validate a why-live path for every
             live actor against the snapshot invariants, and require
             the watchdog to flag the planted leak — exit nonzero on
             any failure (the verify-skill smoke)
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


# ------------------------------------------------------------------- #
# Sources
# ------------------------------------------------------------------- #


def _fetch(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as rsp:
        return json.loads(rsp.read().decode())


def snapshot_from_url(base: str, merged: bool) -> dict:
    base = base.rstrip("/")
    suffix = "/snapshot?merged=1" if merged else "/snapshot"
    return _fetch(base + suffix)


def why_live_from_url(base: str, actor: str) -> dict:
    import urllib.parse

    base = base.rstrip("/")
    return _fetch(base + "/inspect?actor=" + urllib.parse.quote(actor))


def load_snapshot(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    # Accept a flight-recorder dump too: take its newest snapshot.
    if "snapshots" in doc and "actors" not in doc:
        if not doc["snapshots"]:
            raise SystemExit(f"{path}: flight-recorder dump holds no snapshots")
        return doc["snapshots"][-1]
    return doc


# ------------------------------------------------------------------- #
# Demo system (also the selfcheck substrate)
# ------------------------------------------------------------------- #


class DemoSystem:
    """Chain root -> keeper -> kept (the kept actor is retained only
    through the keeper: a 2-hop why-live path), a few busy workers, and
    one planted leak: a worker pinned by a root ref that never receives
    traffic."""

    def __init__(self, leak_waves: int = 3, extra_config: dict = None):
        from uigc_tpu import (
            AbstractBehavior,
            ActorTestKit,
            Behaviors,
            Message,
            NoRefs,
        )

        class Ping(NoRefs):
            pass

        class Give(Message):
            def __init__(self, ref):
                self.ref = ref

            @property
            def refs(self):
                return (self.ref,)

        class Worker(AbstractBehavior):
            def on_message(self, msg):
                return self

        class Keeper(AbstractBehavior):
            def __init__(self, context):
                super().__init__(context)
                self.held = None

            def on_message(self, msg):
                if isinstance(msg, Give):
                    self.held = msg.ref
                return self

        outer = self

        class Root(AbstractBehavior):
            def __init__(self, context):
                super().__init__(context)
                self.keeper = context.spawn(Behaviors.setup(Keeper), "keeper")
                self.kept = context.spawn(Behaviors.setup(Worker), "kept")
                self.leaked = context.spawn(Behaviors.setup(Worker), "leaked")
                self.workers = [
                    context.spawn(Behaviors.setup(Worker), f"w{i}")
                    for i in range(3)
                ]
                outer.names["keeper"] = self.keeper
                outer.names["kept"] = self.kept
                outer.names["leaked"] = self.leaked

            def on_message(self, msg):
                ctx = self.context
                if isinstance(msg, Give):  # hand kept to keeper, drop ours
                    self.keeper.tell(
                        Give(ctx.create_ref(self.kept, self.keeper)), ctx
                    )
                    ctx.release(self.kept)
                    self.kept = None
                elif isinstance(msg, Ping):
                    for worker in self.workers:
                        worker.tell(Ping(), ctx)
                return self

        config = {
            "uigc.crgc.wakeup-interval": 10,
            "uigc.telemetry.inspect": True,
            "uigc.telemetry.leak-waves": leak_waves,
            "uigc.telemetry.snapshot-every": 1,
            "uigc.telemetry.metrics": True,
        }
        if extra_config:
            config.update(extra_config)
        self.names = {}
        self.kit = ActorTestKit(config=config, name="inspect-demo")
        self.root = self.kit.spawn(Behaviors.setup_root(Root), "root")
        self._ping = Ping
        self._give = Give
        self.root.tell(Give(None))  # transfer kept to keeper
        self.churn(rounds=3)

    def churn(self, rounds: int = 1, settle_s: float = 0.08) -> None:
        for _ in range(rounds):
            self.root.tell(self._ping())
            time.sleep(settle_s)

    @property
    def inspector(self):
        return self.kit.system.telemetry.inspector

    def shutdown(self) -> None:
        self.kit.shutdown()


# ------------------------------------------------------------------- #
# Rendering
# ------------------------------------------------------------------- #


def render_why_live(result: dict) -> str:
    name = result.get("name") or result.get("actor")
    verdict = result.get("verdict", "?")
    lines = [f"why-live {name}: {verdict.upper()}"]
    if verdict == "live":
        reasons = ", ".join(result.get("root_reasons", [])) or "?"
        head = result.get("pseudoroot_name") or result.get("pseudoroot")
        src = result.get("parents")
        suffix = f"  [parents: {src}]" if src else ""
        lines.append(f"  pseudoroot {head} ({reasons}){suffix}")
        indent = "  "
        for hop in result.get("path", []):
            indent += "  "
            kind = hop.get("kind")
            weight = hop.get("weight")
            label = f"{kind}" + (f" w={weight}" if weight is not None else "")
            target = hop.get("to_name") or hop.get("to")
            lines.append(f"{indent}-[{label}]-> {target}")
    elif verdict == "collectable":
        lines.append("  " + result.get("note", "unreachable from any pseudoroot"))
    return "\n".join(lines)


def render_snapshot(snap: dict) -> str:
    summary = snap.get("summary", {})
    lines = [
        "snapshot node=%s wave=%s actors=%s edges=%s pseudoroots=%s"
        % (
            snap.get("node") or ",".join(snap.get("nodes", [])),
            snap.get("wave", "?"),
            summary.get("actors"),
            summary.get("edges"),
            summary.get("pseudoroots"),
        )
    ]
    if snap.get("missing_nodes"):
        lines.append("  MISSING nodes: " + ", ".join(snap["missing_nodes"]))
    for key, rec in sorted(snap.get("actors", {}).items()):
        flags = "".join(
            ch
            for ch, on in (
                ("R", rec.get("root")),
                ("B", rec.get("busy")),
                ("L", rec.get("local")),
                ("H", rec.get("halted")),
                ("P", rec.get("pseudoroot")),
            )
            if on
        )
        lines.append(
            f"  {rec.get('name', key):40s} [{flags:5s}] "
            f"recv={rec.get('recv_count', 0)} mailbox={rec.get('mailbox', '?')}"
        )
    return "\n".join(lines)


# ------------------------------------------------------------------- #
# Subcommands
# ------------------------------------------------------------------- #


def cmd_snapshot(args) -> int:
    if args.url:
        snap = snapshot_from_url(args.url, args.merged)
    elif args.from_file:
        snap = load_snapshot(args.from_file)
    else:
        demo = DemoSystem()
        try:
            snap = (
                demo.inspector.merged_snapshot()
                if args.merged
                else demo.inspector.snapshot()
            )
        finally:
            demo.shutdown()
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(snap, fh, indent=2, sort_keys=True, default=repr)
        print(f"wrote {args.out}")
    else:
        print(
            json.dumps(snap, indent=2, sort_keys=True, default=repr)
            if args.json
            else render_snapshot(snap)
        )
    return 0


def cmd_why_live(args) -> int:
    from uigc_tpu.telemetry.inspect import why_live

    if args.url:
        result = why_live_from_url(args.url, args.actor)
    elif args.from_file:
        result = why_live(load_snapshot(args.from_file), args.actor)
    else:
        demo = DemoSystem()
        try:
            result = demo.inspector.why_live(args.actor)
        finally:
            demo.shutdown()
    print(json.dumps(result, indent=2, default=repr) if args.json
          else render_why_live(result))
    return 0 if result.get("verdict") != "unknown" else 1


def cmd_diff(args) -> int:
    from uigc_tpu.telemetry.inspect import diff_snapshots

    result = diff_snapshots(load_snapshot(args.old), load_snapshot(args.new))
    print(json.dumps(result, indent=2, sort_keys=True))
    return 0


def cmd_selfcheck(args) -> int:
    from uigc_tpu.telemetry.inspect import validate_why_live, why_live

    demo = DemoSystem(leak_waves=2)
    problems = []
    try:
        # Let several wakes run so the watchdog sees quiet waves.
        deadline = time.monotonic() + args.timeout
        suspects = []
        while time.monotonic() < deadline and not suspects:
            demo.churn(rounds=1, settle_s=0.05)
            suspects = demo.inspector.watchdog.suspects()
        snap = demo.inspector.snapshot()
        checked = 0
        live_paths = 0
        for key in sorted(snap.get("actors", {})):
            result = why_live(snap, key)
            checked += 1
            if result["verdict"] == "live":
                live_paths += 1
            problems.extend(
                f"{key}: {p}" for p in validate_why_live(snap, result)
            )
        # The inspector's own (parents-based) derivation must agree on
        # the demo's 2-hop retained chain.
        kept_key = None
        for key, rec in snap["actors"].items():
            if rec.get("name", "").endswith("kept"):
                kept_key = key
        if kept_key is None:
            problems.append("demo 'kept' actor missing from snapshot")
        else:
            live = demo.inspector.why_live(kept_key)
            problems.extend(
                f"live-why-live({kept_key}): {p}"
                for p in validate_why_live(snap, live)
            )
            if live.get("verdict") == "live" and len(live.get("path", [])) < 2:
                problems.append(
                    "kept actor should be retained through the keeper "
                    f"(2 hops), got {live.get('path')}"
                )
        suspect_names = [
            snap.get("actors", {}).get(key, {}).get("name", key)
            for key in suspects
        ]
        if not any(name.endswith("leaked") for name in suspect_names):
            problems.append(
                "watchdog never flagged the planted leak "
                f"(suspects={suspect_names})"
            )
        doc = {
            "bench": "graph_inspect_selfcheck",
            "actors_checked": checked,
            "live_paths": live_paths,
            "leak_suspects": suspects,
            "problems": problems,
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    finally:
        demo.shutdown()
    return 1 if problems else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graph-inspect", description=__doc__.splitlines()[0]
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    def add_source(p):
        p.add_argument("--url", help="live system telemetry HTTP base URL")
        p.add_argument(
            "--from", dest="from_file", metavar="FILE",
            help="snapshot (or flight-recorder dump) JSON file",
        )
        p.add_argument(
            "--demo", action="store_true",
            help="spawn the in-process demo system (the default when "
            "neither --url nor --from is given)",
        )
        p.add_argument("--json", action="store_true", help="raw JSON output")

    p = sub.add_parser("snapshot", help="dump a shadow-graph snapshot")
    add_source(p)
    p.add_argument("--merged", action="store_true",
                   help="merge across cluster nodes (snap frames)")
    p.add_argument("-o", "--out", help="write JSON to this file")
    p.set_defaults(fn=cmd_snapshot)

    p = sub.add_parser("why-live", help="print a retaining path")
    p.add_argument("actor", help="actor path, name suffix, or address#uid key")
    add_source(p)
    p.set_defaults(fn=cmd_why_live)

    p = sub.add_parser("diff", help="retained-set diff of two snapshots")
    p.add_argument("old")
    p.add_argument("new")
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser(
        "selfcheck", help="drive a demo system and validate the inspector"
    )
    p.add_argument("--timeout", type=float, default=15.0)
    p.set_defaults(fn=cmd_selfcheck)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
