#!/usr/bin/env python
"""bench-check: regression gate over the committed BENCH_* trajectory.

The repo commits one ``BENCH_<FAMILY>_rNN.json`` artifact per perf
round (FABRIC/SHARD/FOLD/WAKE families).  This tool parses each
family's trajectory, compares the newest run against the prior one
with per-family tolerance bands, and exits nonzero with a readable
delta table when a key metric regressed beyond its band — the cheap
"did this PR quietly lose the 50k frames/s" check the verify pass runs.

Semantics per metric direction:

- ``higher``  throughput-style: FAIL when new < prior * (1 - tol)
- ``lower``   latency-style:    FAIL when new > prior * (1 + tol)
- ``zero``    correctness tally (undercounts): FAIL when new > prior
- ``floor``   absolute minimum: FAIL when new < tol (no trajectory —
              an acceptance bar, e.g. partitioned/replicated >= 1.0)
- ``ceiling`` absolute maximum: FAIL when new > tol

A family with fewer than two committed runs is SKIPped (nothing to
compare), as is a metric whose path stopped existing — bench shapes
drift between rounds, and a missing key must read as "not comparable",
never as a silent pass of something that regressed.  Paths resolve
dotted (``link.batch.frames_per_sec``) with a one-level descent into
nested round documents (the r04 FOLD shape wraps the payload under
``"r4"``).

``--check-regression FILE`` runs the self-test the suite uses: the
given doctored newest-run copy must FAIL against the real trajectory.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


class Metric:
    __slots__ = ("path", "direction", "tolerance")

    def __init__(self, path: str, direction: str, tolerance: float):
        self.path = path
        self.direction = direction
        self.tolerance = tolerance


#: family -> (glob pattern, key metrics).  Tolerances are wide on
#: purpose: these runs come from whatever host the round ran on, and
#: the gate exists to catch step-function losses, not 5% jitter.
FAMILIES: Dict[str, Tuple[str, List[Metric]]] = {
    "FABRIC": (
        "BENCH_FABRIC_r*.json",
        [
            Metric("link.batch.frames_per_sec", "higher", 0.40),
            # r02+: the co-located shm + schema-codec path (the 250k/s
            # acceptance floor and the 500k ROADMAP target live here).
            # SKIPs against rounds that predate the mode.
            Metric("link.shm.frames_per_sec", "higher", 0.40),
            Metric("teardown.actors_per_sec", "higher", 0.40),
        ],
    ),
    "SHARD": (
        "BENCH_SHARD_r*.json",
        [
            Metric("steady.messages_per_sec", "higher", 0.40),
            Metric("post_rebalance_probe.undercounted_entities", "zero", 0.0),
        ],
    ),
    "FOLD": (
        "BENCH_FOLD_r*.json",
        [
            Metric("fold.packed.entries_per_sec", "higher", 0.40),
            Metric("sweep.garbage_actors_per_sec", "higher", 0.40),
        ],
    ),
    "WAKE": (
        "BENCH_WAKE_r*.json",
        [
            Metric("device_per_wake_ms", "lower", 0.40),
            Metric("sweeps_mean", "lower", 0.40),
        ],
    ),
    # Serving scenarios (tools/serving_bench.py): the chat-session
    # fleet through a rolling restart.  lost_acked is a hard zero —
    # a single acked command lost across drain/restart/die is a
    # durability regression, not jitter; restart p99 gets a wide band
    # (it includes rejoin rebalances on whatever host ran the round).
    "SCENARIO": (
        "BENCH_SCENARIO_r*.json",
        [
            Metric("steady.messages_per_sec", "higher", 0.40),
            Metric("restart.p99_latency_s", "lower", 0.60),
            # r02+: the arbiter's deliberate detection windows (settle
            # + reconnect probing) are reported separately as
            # recovery.detection_seconds; this per-entity figure
            # charges only the machinery after the LAST survivor
            # verdict, so the band stays a real regression gate even
            # though the scenario now runs a partition era first.
            Metric("recovery.seconds_per_entity", "lower", 0.60),
            Metric("ledger.lost_acked", "zero", 0.0),
            # r02+ (--partition): ack p99 through the split-brain +
            # heal window gets a wide band; dual activation — an
            # entity sampled live on the quarantined side AND a
            # survivor — is a hard zero, the fencing plane's whole
            # point.  Rounds predating the phase lack the keys and
            # SKIP honestly.
            Metric("partition.heal_p99_latency_s", "lower", 0.60),
            Metric("partition.dual_active_keys", "zero", 0.0),
        ],
    ),
    # Distributed collector (tools/dist_bench.py): 3-node partitioned
    # trace over cross-node garbage cycles.  leaked_actors is a hard
    # zero — a cycle the wave protocol cannot close is a soundness
    # regression, not jitter; throughput gets the usual wide band, and
    # the locality fraction is a structural property of the workload
    # (gated loosely so a full-replica regression — fraction ~1.0 —
    # fails while placement jitter passes).
    "DIST": (
        "BENCH_DIST_r*.json",
        [
            Metric("trace.garbage_actors_per_sec", "higher", 0.40),
            Metric("trace.leaked_actors", "zero", 0.0),
            # Authoritative slots only: a hub actor's owner also holds
            # bare mirrors of everything the hub references; since the
            # PR-15 mirror decay the RESIDENT fraction converges to
            # ~the owned fraction too, and both are gated — owned by
            # trajectory, resident by the absolute 0.7 acceptance bar.
            Metric("locality.max_node_owned_fraction", "lower", 0.60),
            # r02+ (the PR-15 communication-plane rebuild): the
            # partitioned trace must meet or beat the replicated fold
            # measured in the SAME run, termination must stay in the
            # 1-2 round regime, mark bytes get a trajectory band, and
            # the resident-population bar catches full-replica
            # regressions.  Rounds predating the keys SKIP honestly.
            Metric("trace.speedup_vs_replicated", "floor", 1.0),
            Metric("trace.rounds_per_wave", "ceiling", 2.5),
            Metric("trace.boundary_mark_bytes_per_wave", "lower", 0.60),
            Metric("locality.max_node_population_fraction", "ceiling", 0.70),
        ],
    ),
    # Ingress gateway (tools/ingress_bench.py): the front door under a
    # 10x-capacity overload storm plus a connection-scale phase.  The
    # contract is asymmetric on purpose: ADMITTED traffic keeps its p99
    # (absolute ceiling — the overload controller's whole point), SHED
    # traffic gets a clean retryable ERROR (floor on the clean-shed
    # fraction), and acked_then_lost is a hard zero from the debut
    # round — an ACK the client never got the result for is a
    # durability lie, not jitter.  Throughput/connection figures ride
    # the usual wide trajectory bands.
    "INGRESS": (
        "BENCH_INGRESS_r*.json",
        [
            Metric("overload.admitted_p99_ms", "ceiling", 250.0),
            Metric("overload.clean_shed_fraction", "floor", 0.95),
            Metric("overload.acked_then_lost", "zero", 0.0),
            Metric("overload.admitted_per_sec", "higher", 0.40),
            Metric("connections.per_gateway", "floor", 500.0),
            Metric("connections.connect_per_sec", "higher", 0.40),
        ],
    ),
    # Device plane (telemetry/device.py + tools/device_report.py): the
    # TPU-session artifacts gate the same figures the wake-budget
    # explainer decomposes.  Rounds that predate wake_chain_bench (or
    # whole sessions the tunnel outage kept CPU-only) simply lack the
    # keys and SKIP — a missing metric must never read as a pass.
    "DEVICE": (
        "BENCH_TPU_SESSION_r*.json",
        [
            Metric("device_per_wake_ms", "lower", 0.40),
            Metric("device_per_sweep_ms", "lower", 0.40),
            Metric("sweeps_mean", "lower", 0.40),
        ],
    ),
}


def _resolve(doc: Any, path: str) -> Optional[float]:
    """Dotted-path lookup; on a direct miss, descend one level into
    dict values looking for a sub-document where the full path
    resolves (the nested round shape)."""

    def direct(node: Any) -> Optional[float]:
        for part in path.split("."):
            if not isinstance(node, dict) or part not in node:
                return None
            node = node[part]
        if isinstance(node, bool):
            return float(node)
        if isinstance(node, (int, float)):
            return float(node)
        return None

    value = direct(doc)
    if value is not None:
        return value
    if isinstance(doc, dict):
        for sub in doc.values():
            if isinstance(sub, dict):
                value = direct(sub)
                if value is not None:
                    return value
    return None


def trajectory(repo: str, pattern: str) -> List[Tuple[int, str]]:
    """Sorted (round, path) pairs for one family."""
    out = []
    for path in glob.glob(os.path.join(repo, pattern)):
        match = _ROUND_RE.search(path)
        if match:
            out.append((int(match.group(1)), path))
    return sorted(out)


def _load(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def compare_metric(
    metric: Metric, prior: Optional[float], new: Optional[float]
) -> Tuple[str, str]:
    """-> (status, note).  status in PASS/FAIL/SKIP."""
    if metric.direction in ("floor", "ceiling"):
        # Absolute acceptance bars: judged on the newest round alone
        # (the tolerance IS the bar), present-or-SKIP like any metric.
        if new is None:
            return "SKIP", "metric missing in newest"
        if metric.direction == "floor" and new < metric.tolerance:
            return "FAIL", f"below absolute floor {metric.tolerance:g}"
        if metric.direction == "ceiling" and new > metric.tolerance:
            return "FAIL", f"above absolute ceiling {metric.tolerance:g}"
        return "PASS", "absolute bar"
    if metric.direction == "zero" and new is not None and prior is None:
        # A correctness tally is an absolute floor, not a trajectory:
        # its FIRST round must already be zero — a nonzero debut would
        # otherwise grandfather itself in as the comparison baseline.
        if new > metric.tolerance:
            return "FAIL", "nonzero on its first round"
        return "PASS", "first round"
    if prior is None or new is None:
        return "SKIP", "metric missing in " + (
            "both" if prior is None and new is None
            else ("prior" if prior is None else "newest")
        )
    if metric.direction == "higher":
        floor = prior * (1.0 - metric.tolerance)
        if new < floor:
            return "FAIL", f"below floor {floor:.4g}"
        return "PASS", ""
    if metric.direction == "lower":
        ceiling = prior * (1.0 + metric.tolerance)
        if new > ceiling:
            return "FAIL", f"above ceiling {ceiling:.4g}"
        return "PASS", ""
    # zero: a correctness tally that must never grow
    if new > prior + metric.tolerance:
        return "FAIL", f"grew from {prior:g}"
    return "PASS", ""


def check_family(
    repo: str,
    family: str,
    newest_override: Optional[str] = None,
) -> List[Dict[str, Any]]:
    pattern, metrics = FAMILIES[family]
    runs = trajectory(repo, pattern)
    rows: List[Dict[str, Any]] = []
    if len(runs) < 2 and not (newest_override and runs):
        if not runs:
            rows.append(
                {
                    "family": family, "metric": "-", "status": "SKIP",
                    "note": "0 committed run(s); need 2",
                }
            )
            return rows
        # One committed round: no trajectory to band yet, but the
        # zero-direction correctness floors are absolute — they must
        # already hold on the debut round, or a nonzero tally would
        # grandfather itself in as the future comparison baseline.
        new_round, new_path = runs[-1]
        new_doc = _load(new_path)
        for metric in metrics:
            if metric.direction not in ("zero", "floor", "ceiling"):
                rows.append(
                    {
                        "family": family, "metric": metric.path,
                        "status": "SKIP",
                        "note": "1 committed run(s); need 2",
                    }
                )
                continue
            new = _resolve(new_doc, metric.path) if new_doc else None
            if new is None:
                status, note = "SKIP", "metric missing in newest"
            else:
                status, note = compare_metric(metric, None, new)
            rows.append(
                {
                    "family": family,
                    "metric": metric.path,
                    "prior": None,
                    "new": new,
                    "rounds": f"r{new_round:02d}",
                    "delta": "",
                    "tolerance": metric.tolerance,
                    "direction": metric.direction,
                    "status": status,
                    "note": note,
                }
            )
        return rows
    if newest_override:
        prior_round, prior_path = runs[-1]
        new_round, new_path = prior_round + 1, newest_override
    else:
        (prior_round, prior_path), (new_round, new_path) = runs[-2], runs[-1]
    prior_doc, new_doc = _load(prior_path), _load(new_path)
    for metric in metrics:
        prior = _resolve(prior_doc, metric.path) if prior_doc else None
        new = _resolve(new_doc, metric.path) if new_doc else None
        status, note = compare_metric(metric, prior, new)
        delta = ""
        if prior not in (None, 0) and new is not None:
            delta = f"{(new - prior) / prior * 100.0:+.1f}%"
        rows.append(
            {
                "family": family,
                "metric": metric.path,
                "prior": prior,
                "new": new,
                "rounds": f"r{prior_round:02d}->r{new_round:02d}",
                "delta": delta,
                "tolerance": metric.tolerance,
                "direction": metric.direction,
                "status": status,
                "note": note,
            }
        )
    return rows


def render_table(rows: List[Dict[str, Any]]) -> str:
    def num(v: Any) -> str:
        return f"{v:.4g}" if isinstance(v, float) else "-"

    widths = (7, 44, 12, 12, 8, 11, 6)
    header = ("family", "metric", "prior", "new", "delta", "rounds", "status")
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(header, widths)),
        "  ".join("-" * w for w in widths),
    ]
    for row in rows:
        cells = (
            row["family"],
            row["metric"],
            num(row.get("prior")),
            num(row.get("new")),
            row.get("delta", "") or "-",
            row.get("rounds", "-"),
            row["status"],
        )
        line = "  ".join(str(c).ljust(w) for c, w in zip(cells, widths))
        if row.get("note"):
            line += f"  ({row['note']})"
        lines.append(line)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench-check", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "--repo",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="repo root holding the BENCH_*.json trajectory",
    )
    parser.add_argument(
        "--family",
        choices=sorted(FAMILIES),
        action="append",
        help="check only these families (default: all)",
    )
    parser.add_argument(
        "--check-regression",
        metavar="FILE",
        help="treat FILE as the newest run of its family (self-test: a "
        "doctored copy must FAIL)",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the rows as JSON"
    )
    args = parser.parse_args(argv)

    families = args.family or sorted(FAMILIES)
    override_family = None
    if args.check_regression:
        base = os.path.basename(args.check_regression)
        for name, (pattern, _metrics) in FAMILIES.items():
            if base.startswith(pattern.split("_r")[0]):
                override_family = name
        if override_family is None:
            print(
                f"bench-check: cannot infer family of {base!r}",
                file=sys.stderr,
            )
            return 2
        families = [override_family]

    rows: List[Dict[str, Any]] = []
    for family in families:
        rows.extend(
            check_family(
                args.repo,
                family,
                newest_override=(
                    args.check_regression if family == override_family else None
                ),
            )
        )
    if args.json:
        print(json.dumps(rows, indent=2, sort_keys=True))
    else:
        print(render_table(rows))
    failed = [r for r in rows if r["status"] == "FAIL"]
    if failed:
        print(
            f"bench-check: {len(failed)} metric(s) regressed beyond tolerance",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
