"""Fold + sweep throughput benchmark for the array shadow graph.

Measures, at graph scale, the two collector hot paths the reference runs
per 50ms wake (LocalGC.scala:149-177 / ShadowGraph.java:75-125,273-289):

- **fold**: merging a drained batch of mutator entries — the per-entry
  scalar path (``merge_entry`` loop, the pre-r4 collector) vs the batched
  vectorized path (``merge_entries``);
- **sweep**: freeing every garbage slot after a trace — timed at >=1M
  garbage actors through the vectorized ``_free_slots_batch``.

Prints one JSON object; commit the output as ``BENCH_FOLD_r{N}.json``.

Usage: python tools/fold_bench.py [--actors 1000000] [--entries 200000]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from uigc_tpu.engines.crgc import refob as refob_info
from uigc_tpu.engines.crgc.arrays import ArrayShadowGraph
from uigc_tpu.engines.crgc.refob import CrgcRefob
from uigc_tpu.engines.crgc.state import CrgcContext, Entry
from uigc_tpu.ops import trace as trace_ops


class FakeSystem:
    def __init__(self, address="uigc://foldbench"):
        self.address = address


class FakeCell:
    __slots__ = ("uid", "path", "system")
    _count = 0

    def __init__(self, system):
        FakeCell._count += 1
        self.uid = FakeCell._count
        self.path = f"/bench/{self.uid}"
        self.system = system

    def tell(self, msg):
        pass


def synth_entries(cells, rng, n_entries, context, fanout=4):
    """Entry stream shaped like a busy system: every entry snapshots one
    actor (busy bit, recv count), creates a few refs to random targets,
    and deactivates a couple of older ones."""
    system_refs = [CrgcRefob(c) for c in cells]
    entries = []
    n = len(cells)
    owners = rng.integers(0, n, size=(n_entries, fanout))
    targets = rng.integers(0, n, size=(n_entries, fanout))
    deact = rng.integers(0, n, size=(n_entries, 2))
    selfs = rng.integers(0, n, size=n_entries)
    for i in range(n_entries):
        e = Entry(context)
        e.self_ref = system_refs[selfs[i]]
        e.is_busy = bool(i & 1)
        e.is_root = False
        e.recv_count = 3
        for j in range(fanout):
            e.created_owners[j] = system_refs[owners[i, j]]
            e.created_targets[j] = system_refs[targets[i, j]]
        for j in range(2):
            e.updated_refs[j] = system_refs[deact[i, j]]
            # packed RefobInfo: two sends, deactivated
            info = refob_info.inc_send_count(
                refob_info.inc_send_count(refob_info.ACTIVE_REFOB)
            )
            e.updated_infos[j] = refob_info.deactivate(info)
        entries.append(e)
    return entries


def graphs_agree(a, b) -> bool:
    """One definition of graph equality for every parity check."""
    import numpy as _np

    return bool(
        _np.array_equal(a.flags, b.flags)
        and _np.array_equal(a.recv_count, b.recv_count)
        and _np.array_equal(a.supervisor, b.supervisor)
        and a.edge_of.key_set() == b.edge_of.key_set()
        and all(
            a.edge_weight[a.edge_of[k]] == b.edge_weight[b.edge_of[k]]
            for k in a.edge_of.key_set()
        )
    )


def bench_fold(n_actors, n_entries, seed=0):
    context = CrgcContext(delta_graph_size=64, entry_field_size=8)
    system = FakeSystem()
    cells = [FakeCell(system) for _ in range(n_actors)]

    results = {}
    modes = ("scalar", "batched")
    if not hasattr(ArrayShadowGraph, "merge_entries"):
        modes = ("scalar",)  # running against a pre-r4 tree
    for mode in modes:
        graph = ArrayShadowGraph(context, system.address, use_device=False)
        # pre-intern every actor so both modes measure fold, not interning
        for c in cells:
            graph.slot_for(c)
        # identical entry stream for both modes
        rng = np.random.default_rng(seed)
        entries = synth_entries(cells, rng, n_entries, context)
        t0 = time.perf_counter()
        if mode == "scalar":
            for e in entries:
                graph.merge_entry(e)
        else:
            graph.merge_entries(entries)
        dt = time.perf_counter() - t0
        results[mode] = {
            "seconds": round(dt, 4),
            "entries_per_sec": round(n_entries / dt, 1),
            "edges_after": len(graph.edge_of),
        }
        results[f"_graph_{mode}"] = graph
    # --- packed plane: the same logical stream as int64 rows ---------- #
    if hasattr(ArrayShadowGraph, "merge_packed"):
        from uigc_tpu.engines.crgc.packed import PackedPlane, row_width

        graph = ArrayShadowGraph(context, system.address, use_device=False)
        plane = PackedPlane(context.entry_field_size)
        by_uid = {c.uid: c for c in cells}
        graph.attach_packed_plane(plane, by_uid.get)
        # steady state: pre-intern and pre-map every uid (first-contact
        # interning is bounded by spawn rate, not flush rate — not what
        # this benchmark measures)
        slots = np.array([graph.slot_for(c) for c in cells], dtype=np.int64)
        uids = np.array([c.uid for c in cells], dtype=np.int64)
        graph._uid_to_slot = np.full(int(uids.max()) + 1, -1, dtype=np.int64)
        graph._uid_to_slot[uids] = slots
        graph._slot_uid[slots] = uids
        rng = np.random.default_rng(seed)
        E = context.entry_field_size
        fanout = 4
        n = len(cells)
        owners = rng.integers(0, n, size=(n_entries, fanout))
        targets = rng.integers(0, n, size=(n_entries, fanout))
        deact = rng.integers(0, n, size=(n_entries, 2))
        selfs = rng.integers(0, n, size=n_entries)
        uid_arr = np.array([c.uid for c in cells], dtype=np.int64)
        W = row_width(E)
        rows = np.full((n_entries, W), -1, dtype=np.int64)
        rows[:, 0] = np.arange(n_entries)
        rows[:, 1] = uid_arr[selfs]
        rows[:, 2] = np.arange(n_entries) & 1  # busy alternates, never root
        rows[:, 3] = 3
        for j in range(fanout):
            rows[:, 4 + 2 * j] = uid_arr[owners[:, j]]
            rows[:, 4 + 2 * j + 1] = uid_arr[targets[:, j]]
        info = refob_info.deactivate(
            refob_info.inc_send_count(
                refob_info.inc_send_count(refob_info.ACTIVE_REFOB)
            )
        )
        ubase = 4 + 3 * E
        for j in range(2):
            rows[:, ubase + 2 * j] = uid_arr[deact[:, j]]
            rows[:, ubase + 2 * j + 1] = info
        t0 = time.perf_counter()
        graph.merge_packed(rows)
        dt = time.perf_counter() - t0
        results["packed"] = {
            "seconds": round(dt, 4),
            "entries_per_sec": round(n_entries / dt, 1),
            "edges_after": len(graph.edge_of),
        }
        results["_graph_packed"] = graph
        # Parity vs the batched object fold — BEFORE the warm re-merge
        # below mutates the packed graph past the object one.
        gb = results.get("_graph_batched")
        if gb is not None:
            results["packed_agrees"] = graphs_agree(gb, graph)
        # steady state: the same stream again, edges now resident (the
        # all-new-edges cold fold above is the worst case; a running
        # system mostly re-touches existing pairs)
        warm = np.array(rows)
        t0 = time.perf_counter()
        graph.merge_packed(warm)
        dt = time.perf_counter() - t0
        results["packed_warm"] = {
            "seconds": round(dt, 4),
            "entries_per_sec": round(n_entries / dt, 1),
        }

    ga = results.pop("_graph_scalar")
    gp = results.pop("_graph_packed", None)
    gb = results.pop("_graph_batched", None)
    if gb is not None and gp is not None:
        results["speedup_packed_vs_scalar"] = round(
            results["packed"]["entries_per_sec"]
            / results["scalar"]["entries_per_sec"],
            2,
        )
        results["speedup_packed_vs_batched"] = round(
            results["packed"]["entries_per_sec"]
            / results["batched"]["entries_per_sec"],
            2,
        )
    if gb is not None:
        # the two modes must agree on the resulting graph
        results["modes_agree"] = graphs_agree(ga, gb)
        results["speedup"] = round(
            results["batched"]["entries_per_sec"]
            / results["scalar"]["entries_per_sec"],
            2,
        )
    return results, gb if gb is not None else ga, cells


def bench_sweep(graph, cells, n_actors, seed=1):
    """Mark ~all actors garbage (no roots/busy/recv) and time the sweep."""
    # silence: no roots, no busy, no pending receives -> everything
    # non-interned seeds... make all interned, none busy/root, recv 0
    graph.flags[: len(cells)] |= trace_ops.FLAG_INTERNED
    graph.flags[: len(cells)] &= ~np.uint8(
        int(trace_ops.FLAG_BUSY) | int(trace_ops.FLAG_ROOT)
    )
    graph.recv_count[:] = 0
    n_edges_before = len(graph.edge_of)
    t0 = time.perf_counter()
    n_freed = graph.trace(should_kill=True)
    dt = time.perf_counter() - t0
    return {
        "garbage_freed": n_freed,
        "edges_freed": n_edges_before - len(graph.edge_of),
        "seconds": round(dt, 4),
        "garbage_actors_per_sec": round(n_freed / dt, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--actors", type=int, default=1_000_000)
    ap.add_argument("--entries", type=int, default=200_000)
    args = ap.parse_args()

    fold, graph, cells = bench_fold(args.actors, args.entries)
    sweep = bench_sweep(graph, cells, args.actors)
    print(
        json.dumps(
            {
                "bench": "fold+sweep",
                "n_actors": args.actors,
                "n_entries": args.entries,
                "fold": fold,
                "sweep": sweep,
            }
        )
    )


if __name__ == "__main__":
    main()
