"""Measure per-wake Pallas-layout maintenance: full repack vs incremental.

Round 1 re-ran prepare_chunks (a full lexsort over every live pair)
before nearly every collector wake on a churning graph (VERDICT r1, weak
item 3).  The incremental layout (ops/pallas_incremental.py) replaces
that with O(changes) maintenance: in-place masking for deletes plus a
small delta pack for inserts.  This tool measures both costs on the same
synthetic power-law graph and churn stream — host-side work only, so the
numbers are platform-independent (the kernel itself is benchmarked by
bench.py).

Usage: python tools/pack_bench.py [--n 1000000] [--churn 10000] [--wakes 5]
Prints one JSON line; committed artifacts live in BENCH_PACK_r*.json.
"""

import argparse
import json
import statistics
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--churn", type=int, default=10_000, help="pair transitions per wake")
    ap.add_argument("--wakes", type=int, default=5)
    args = ap.parse_args()

    from uigc_tpu.models import powerlaw_actor_graph
    from uigc_tpu.ops import pallas_incremental as pinc
    from uigc_tpu.ops import pallas_trace

    graph = powerlaw_actor_graph(args.n, seed=0, garbage_fraction=0.5)
    src = graph["edge_src"].astype(np.int32)
    dst = graph["edge_dst"].astype(np.int32)
    w = graph["edge_weight"]
    sup = graph["supervisor"]
    rng = np.random.default_rng(1)

    # What round 1 paid on every wake whose interval saw any edge insert:
    full_times = []
    for _ in range(args.wakes):
        t0 = time.perf_counter()
        pallas_trace.prepare_chunks(src, dst, w, sup, args.n, pad_blocks_pow2=True)
        full_times.append(time.perf_counter() - t0)

    # What the incremental layout pays per wake for the same churn:
    layout = pinc.IncrementalPallasLayout(args.n)
    layout.rebuild(src, dst, w, sup)
    rebuild_s = layout.stats["pack_s"]

    live = np.nonzero(w > 0)[0]
    seen_inserts = set()
    inc_times = []
    for _ in range(args.wakes):
        # Half deletes of existing live edges, half fresh inserts.  Kill
        # candidates are removed from the live pool so a later wake never
        # re-deletes the same edge (which would hit the layout's anomaly
        # path instead of doing real deletion work); inserts are deduped
        # for the same reason.
        kill = rng.choice(live, size=args.churn // 2, replace=False)
        live = np.setdiff1d(live, kill, assume_unique=True)
        fresh = []
        while len(fresh) < args.churn // 2:
            pair = (int(rng.integers(0, args.n)), int(rng.integers(0, args.n)))
            if pair not in seen_inserts:
                seen_inserts.add(pair)
                fresh.append(pair)
        log = [(False, int(src[eid]), int(dst[eid]), pinc.EDGE) for eid in kill]
        log += [(True, s, d, pinc.EDGE) for s, d in fresh]
        t0 = time.perf_counter()
        # the production path: batched log replay (arrays.py feeds the
        # collector's _pair_log through apply_log the same way)
        layout.apply_log(log)
        # everything trace() does on the host except the kernel launch
        layout.prepare_wake()
        inc_times.append(time.perf_counter() - t0)

    result = {
        "metric": "pack_ms_per_wake",
        "n_actors": args.n,
        "n_pairs": int((w > 0).sum() + (sup >= 0).sum()),
        "churn_per_wake": args.churn,
        "full_repack_ms_p50": round(statistics.median(full_times) * 1e3, 2),
        "incremental_ms_p50": round(statistics.median(inc_times) * 1e3, 2),
        "speedup": round(
            statistics.median(full_times) / statistics.median(inc_times), 1
        ),
        "one_time_rebuild_ms": round(rebuild_s * 1e3, 2),
        "anomalies": layout.stats["anomalies"],
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
