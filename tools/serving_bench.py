"""Serving bench: a chat-session fleet through a rolling restart.

The scenario ROADMAP item 4 names: N chat-session entities (sharded,
journaled) served by a 3-node cluster under sustained acked traffic,
while the cluster is rolled node by node — drain, terminate, restart,
rejoin — and finally one node is killed abruptly (``NodeFabric.die``).
The client keeps a ledger of every ACKED command; the run fails unless
the final per-session counts cover every acked command (journal replay
verified against the ledger: zero acknowledged state lost).

Phases and the figures they print:

1. **steady**   — sustained ``say`` traffic with per-message acks:
   messages/sec plus ack-latency p50/p99;
2. **restart**  — every data node drained + restarted in sequence with
   traffic still running: p99 ack latency THROUGH the restart window,
   per-node drain + rejoin wall time (runs BEFORE the partition phase
   so its figures stay comparable with the r01 trajectory);
3. **partition** (``--partition``) — a symmetric partition isolates one
   (already once-restarted) node for >= 10 heartbeat windows
   mid-traffic: the split-brain resolver downs the minority
   (quarantine: entities drained to the journal, append plane frozen),
   the majority absorbs its shards and keeps serving, then the link
   heals and the ``mship`` handshake readmits the loser.  Figures:
   verdict/heal wall time, ack p99 through the partition+heal window,
   the sampled count of entities concurrently active on two nodes
   (hard zero), and the fence counters (stale appends refused,
   recovery conflicts quarantined);
4. **crash**    — one node killed abruptly; survivors journal-recover
   its sessions: recovery seconds and seconds-per-entity;
5. **ledger**   — per-session floor check: ``lost_acked`` must be 0.

Prints one JSON object; commit as ``BENCH_SCENARIO_r{N}.json``
(bench_check's SCENARIO family gates messages_per_sec, restart p99,
lost_acked, heal p99 and the dual-activation hard zero across rounds).

Usage: python tools/serving_bench.py [--sessions 300] [--seconds 4]
       [--partition] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from uigc_tpu import ActorSystem, ClusterSharding, Entity  # noqa: E402
from uigc_tpu.runtime.behaviors import RawBehavior  # noqa: E402
from uigc_tpu.runtime.node import NodeFabric  # noqa: E402
from uigc_tpu.utils import events  # noqa: E402
from uigc_tpu.utils.validation import require  # noqa: E402


def base_config(journal_dir: str, partition: bool = False) -> dict:
    config = {
        "uigc.crgc.wakeup-interval": 50,
        "uigc.crgc.egress-finalize-interval": 10,
        "uigc.crgc.shadow-graph": "array",
        "uigc.crgc.num-nodes": 3,
        "uigc.cluster.tick-interval": 40,
        "uigc.cluster.handoff-retry": 150,
        # Slack for loaded hosts: an expired hold lets on-demand
        # recovery race an in-flight migration (the lost-ack class the
        # ledger would catch); the timeout is only a wedge safety valve.
        "uigc.cluster.hold-timeout": 15000,
        # The durability plane under test:
        "uigc.cluster.journal-dir": journal_dir,
        "uigc.cluster.journal-fsync": "interval",
        "uigc.cluster.journal-snapshot-every": 32,
        # Bounded end-to-end: entity mailboxes block (propagating to
        # writer queues), cluster buffers shed-with-accounting.
        "uigc.cluster.entity-mailbox-limit": 4096,
        "uigc.runtime.overflow-policy": "block",
        "uigc.runtime.throughput": 256,
        "uigc.node.max-batch-frames": 1024,
        "uigc.node.writer-queue-limit": 32768,
    }
    if partition:
        # Partition detection needs the heartbeat plane (a cut produces
        # silence, never EOF) and the split-brain resolver on its
        # default keep-majority strategy.  The detector is deliberately
        # LENIENT (default threshold, a generous pause): the post-heal
        # rebalance floods the regained shards, and block-policy
        # backpressure can stall a RECEIVE thread long enough that
        # arriving heartbeats sit unrecorded in the kernel buffer — a
        # tight pause reads that as death and cascades into spurious
        # splits.  Reconnect retries are the second line: even a false
        # verdict then self-heals through the same heal-rejoin +
        # handshake machinery a real partition uses, instead of
        # leaving a permanently dark link nobody re-dials.
        config.update(
            {
                "uigc.node.heartbeat-interval": 50,
                "uigc.node.phi-threshold": 8.0,
                "uigc.node.heartbeat-pause": 2500,
                "uigc.node.reconnect-retries": 4,
                "uigc.node.reconnect-backoff": 100,
                "uigc.cluster.sbr-strategy": "keep-majority",
                "uigc.cluster.sbr-settle": 300,
            }
        )
    return config


class ChatSession(Entity):
    """One conversation: an append-only transcript tail + count."""

    def __init__(self, ctx, key, state):
        super().__init__(ctx, key)
        state = state or {}
        self.count = state.get("count", 0)
        self.tail = state.get("tail", [])

    def receive(self, msg):
        kind = msg[0]
        if kind == "say":
            # ("say", text, t_sent, reply_cell)
            self.count += 1
            self.tail.append(msg[1])
            if len(self.tail) > 8:
                del self.tail[0]
            msg[3].tell(("ack", self.key, self.count, msg[2]))
        elif kind == "probe":
            msg[1].tell(("hist", self.key, self.count))
        return self

    def snapshot_state(self):
        return {"count": self.count, "tail": list(self.tail)}


def session_factory(ctx, key, state):
    return ChatSession(ctx, key, state)


class Ledger(RawBehavior):
    """Client-side truth: per-session highwater of ACKED counts, plus
    ack latency samples."""

    def __init__(self):
        self.acked = {}
        self.hist = {}
        self.latencies = []
        self._lock = threading.Lock()

    def on_message(self, msg):
        if not isinstance(msg, tuple) or not msg:
            return None
        if msg[0] == "ack":
            _kind, key, count, t_sent = msg
            now = time.perf_counter()
            with self._lock:
                if count > self.acked.get(key, 0):
                    self.acked[key] = count
                self.latencies.append(now - t_sent)
        elif msg[0] == "hist":
            with self._lock:
                self.hist[msg[1]] = msg[2]
        return None

    def ack_total(self):
        with self._lock:
            return sum(self.acked.values())

    def take_latencies(self):
        with self._lock:
            out = self.latencies
            self.latencies = []
            return out


def percentile(samples, p):
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(round(p / 100.0 * (len(ordered) - 1))))
    return ordered[idx]


class Node:
    __slots__ = ("name", "fabric", "system", "cluster", "region", "port")

    def __init__(self, name: str, config: dict, plan=None):
        self.name = name
        self.fabric = NodeFabric(fault_plan=plan)
        self.system = ActorSystem(None, name=name, config=config, fabric=self.fabric)
        self.port = self.fabric.listen()
        self.cluster = ClusterSharding.attach(self.system)
        self.region = self.cluster.start("chat", session_factory)


def settle(predicate, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return predicate()


def run(n_sessions: int, phase_seconds: float, partition: bool = False) -> dict:
    journal_dir = tempfile.mkdtemp(prefix="uigc-serving-journal-")
    recovered = []

    verdicts = []

    def listener(name, fields):
        if name == events.JOURNAL_RECOVERED:
            recovered.append(fields)
        elif name == events.SBR_DECISION:
            # the instant a settled membership verdict executed
            # (listeners run synchronously on the committing thread)
            verdicts.append((time.perf_counter(), fields))

    plan = None
    if partition:
        from uigc_tpu.runtime.faults import FaultPlan

        plan = FaultPlan(2026)
    config = base_config(journal_dir, partition=partition)
    nodes = {
        name: Node(name, config, plan)
        for name in ("serve-a", "serve-b", "serve-c")
    }
    a = nodes["serve-a"]
    result = {"sessions": n_sessions, "journal_dir": journal_dir}
    stop = threading.Event()
    sent_total = [0]
    keys = [f"session-{i}" for i in range(n_sessions)]

    ledger = Ledger()
    ledger_cell = a.system.spawn_system_raw(ledger, "ledger")

    def frontend():
        # One ingress frontend on node a drives the whole keyspace —
        # every message exercises routing, ~2/3 cross a link.
        i = 0
        cluster = a.cluster
        while not stop.is_set():
            key = keys[i % n_sessions]
            cluster.entity_ref("chat", key).tell(
                ("say", f"m{i}", time.perf_counter(), ledger_cell)
            )
            sent_total[0] += 1
            i += 1
            if i % 64 == 0:
                time.sleep(0.001)  # breathe: let acks drain

    try:
        for other in ("serve-b", "serve-c"):
            a.fabric.connect("127.0.0.1", nodes[other].port)
        nodes["serve-b"].fabric.connect("127.0.0.1", nodes["serve-c"].port)
        require(
            settle(lambda: all(len(n.cluster.members()) == 3 for n in nodes.values())),
            "bench.membership",
            "3-node membership never settled",
        )
        for key in keys:
            a.cluster.entity_ref("chat", key).tell(
                ("say", "warm", time.perf_counter(), ledger_cell)
            )
        require(
            settle(
                lambda: sum(n.region.active_count() for n in nodes.values())
                == n_sessions
            ),
            "bench.warmup",
            "keyspace never fully activated",
        )

        # -- phase 1: steady state ---------------------------------- #
        thread = threading.Thread(target=frontend, daemon=True)
        ledger.take_latencies()
        t0 = time.perf_counter()
        thread.start()
        time.sleep(phase_seconds)
        steady_sent = sent_total[0]
        steady_s = time.perf_counter() - t0
        lat = ledger.take_latencies()
        result["steady"] = {
            "seconds": steady_s,
            "messages": steady_sent,
            "messages_per_sec": steady_sent / steady_s,
            "ack_p50_ms": percentile(lat, 50) * 1e3,
            "ack_p99_ms": percentile(lat, 99) * 1e3,
            "ack_samples": len(lat),
        }

        events.recorder.enable()
        events.recorder.add_listener(listener)

        # -- phase 2: rolling restart under traffic ----------------- #
        restart_stats = []
        window_lat = []
        for name in ("serve-b", "serve-c"):
            node = nodes[name]
            t_drain = time.perf_counter()
            drained = node.fabric.drain(timeout_s=30.0)
            drain_s = time.perf_counter() - t_drain
            node.system.terminate(timeout_s=10.0)
            require(
                settle(
                    lambda: node.system.address not in a.cluster.members(),
                    30.0,
                ),
                "bench.depart",
                f"{name} never left the member set",
            )
            t_join = time.perf_counter()
            fresh = Node(name, config, plan)
            nodes[name] = fresh
            fresh.fabric.connect("127.0.0.1", a.port)
            for other_name, other in nodes.items():
                if other_name not in (name, "serve-a"):
                    fresh.fabric.connect("127.0.0.1", other.port)
            require(
                settle(
                    lambda: len(fresh.cluster.members()) == 3
                    and fresh.region.active_count() > 0
                    and all(
                        n.cluster.migrations.pending_count() == 0
                        for n in nodes.values()
                    ),
                    60.0,
                ),
                "bench.rejoin",
                f"{name} never rejoined/rebalanced",
            )
            join_s = time.perf_counter() - t_join
            restart_stats.append(
                {"node": name, "drained": drained, "drain_s": drain_s, "rejoin_s": join_s}
            )
            window_lat.extend(ledger.take_latencies())
        result["restart"] = {
            "nodes_rolled": len(restart_stats),
            "per_node": restart_stats,
            "drain_s_mean": sum(r["drain_s"] for r in restart_stats)
            / len(restart_stats),
            "rejoin_s_mean": sum(r["rejoin_s"] for r in restart_stats)
            / len(restart_stats),
            "p99_latency_s": percentile(window_lat, 99),
            "p50_latency_s": percentile(window_lat, 50),
            "ack_samples": len(window_lat),
        }

        # -- phase 3 (--partition): split-brain + heal under traffic - #
        if partition:
            b = nodes["serve-b"]
            c = nodes["serve-c"]
            hb_s = config["uigc.node.heartbeat-interval"] / 1000.0
            doomed_b = sum(
                1 for k in keys if a.cluster.home_of(k) == b.system.address
            )
            ledger.take_latencies()
            t_cut = time.perf_counter()
            plan.isolate(b.system.address)
            require(
                settle(
                    lambda: b.system.address not in a.cluster.members()
                    and b.system.address not in c.cluster.members()
                    and b.cluster.quarantined,
                    60.0,
                ),
                "bench.partition-verdict",
                "split-brain verdicts never settled",
            )
            verdict_s = time.perf_counter() - t_cut
            require(
                settle(
                    lambda: b.region.active_count() == 0
                    and b.cluster.journal.frozen,
                    30.0,
                ),
                "bench.quarantine",
                "minority never finished its quarantine drain",
            )
            # Majority absorbed the minority's shards and keeps serving.
            require(
                settle(
                    lambda: a.cluster.migrations.pending_count() == 0
                    and c.cluster.migrations.pending_count() == 0,
                    60.0,
                ),
                "bench.partition-absorb",
                "majority never absorbed the minority's shards",
            )
            # Keep the cut open for >= 10 heartbeat windows in total,
            # sampling for dual activation the whole time: a key active
            # on the quarantined side AND a survivor is the divergence
            # the fencing plane exists to make impossible.
            dual_active = 0
            deadline = t_cut + max(10 * hb_s, verdict_s) + 0.5
            while time.perf_counter() < deadline:
                quarantined_keys = set(b.region.active_keys())
                for survivor in (a, c):
                    dual_active = max(
                        dual_active,
                        len(
                            quarantined_keys
                            & set(survivor.region.active_keys())
                        ),
                    )
                time.sleep(0.05)
            partition_window_s = time.perf_counter() - t_cut
            fence_rejected_appends = b.cluster.journal.stats()[
                "fence_rejected_appends"
            ]
            # -- heal: mend the links, handshake, readmit ----------- #
            t_heal = time.perf_counter()
            plan.heal(b.system.address, "*")
            b.fabric.connect("127.0.0.1", a.port)
            b.fabric.connect("127.0.0.1", c.port)
            require(
                settle(
                    lambda: not b.cluster.quarantined
                    and all(
                        len(n.cluster.members()) == 3 for n in nodes.values()
                    )
                    and all(
                        n.cluster.migrations.pending_count() == 0
                        for n in nodes.values()
                    ),
                    60.0,
                ),
                "bench.heal",
                "the partitioned node never rejoined after the heal",
                quarantined=b.cluster.quarantined,
                members={
                    n.name: n.cluster.members() for n in nodes.values()
                },
                pending={
                    n.name: n.cluster.migrations.pending_count()
                    for n in nodes.values()
                },
                fabric_members={
                    n.name: n.fabric.members() for n in nodes.values()
                },
                fabric_crashed={
                    n.name: sorted(n.fabric.crashed) for n in nodes.values()
                },
            )
            heal_s = time.perf_counter() - t_heal
            heal_lat = ledger.take_latencies()
            bookkeeper = b.system.engine.bookkeeper
            result["partition"] = {
                "victim": b.name,
                "verdict_seconds": verdict_s,
                "partition_window_s": partition_window_s,
                "heartbeat_windows": partition_window_s / hb_s,
                "dual_active_keys": dual_active,
                "fence_rejected_appends": fence_rejected_appends,
                "fence_conflicts_quarantined": sum(
                    n.cluster.journal.stats()["fence_conflicts"]
                    for n in nodes.values()
                ),
                "sessions_homed_on_victim": doomed_b,
                "heal_seconds": heal_s,
                "heal_p99_latency_s": percentile(heal_lat, 99),
                "heal_p50_latency_s": percentile(heal_lat, 50),
                "ack_samples": len(heal_lat),
                "rejoined_collector_clean": int(
                    not bookkeeper.downed_gcs and not b.cluster.quarantined
                ),
                "cluster_fence": a.cluster.current_fence,
            }


        # -- phase 4: abrupt kill + journal recovery ---------------- #
        victim = nodes["serve-c"]
        doomed = sum(
            1 for k in keys if a.cluster.home_of(k) == victim.system.address
        )
        base_recovered = len(recovered)  # partition/restart phases recover too
        base_verdicts = len(verdicts)
        t_crash = time.perf_counter()
        victim.fabric.die()
        require(
            settle(
                lambda: victim.system.address not in a.cluster.members(), 30.0
            ),
            "bench.death",
            "victim never declared dead",
        )
        require(
            settle(lambda: len(recovered) - base_recovered >= doomed, 60.0),
            "bench.recovery",
            "journal recovery never covered the victim's sessions",
            recovered=len(recovered) - base_recovered,
            doomed=doomed,
        )
        recovery_s = time.perf_counter() - t_crash
        stop.set()
        thread.join(timeout=5)
        crash_recovered = recovered[base_recovered:]
        # With the arbiter on (the default), the membership verdict is
        # DELIBERATELY deferred by the sbr-settle window (plus any
        # reconnect probing); detection is that wait, recovery is the
        # machinery after it.  Split the two: ``seconds`` stays the
        # full user-visible outage (crash -> every session recovered),
        # ``seconds_per_entity`` charges the recovery plane only for
        # the part it controls — otherwise the fixed detection
        # windows, divided by the session count, would read as a
        # per-entity replay regression.  The LAST survivor verdict is
        # the start line: the victim's shards split across survivors,
        # and no inheritor can recover before its own verdict.
        crash_verdicts = verdicts[base_verdicts:]
        t_verdict = (
            max(t for t, _f in crash_verdicts) if crash_verdicts else t_crash
        )
        machinery_s = max(0.0, (t_crash + recovery_s) - t_verdict)
        result["recovery"] = {
            "entities": len(crash_recovered),
            "seconds": recovery_s,
            "detection_seconds": max(0.0, t_verdict - t_crash),
            "seconds_per_entity": machinery_s / max(1, len(crash_recovered)),
            "replay_s_mean": (
                sum(f.get("duration_s") or 0.0 for f in crash_recovered)
                / max(1, len(crash_recovered))
            ),
        }

        # -- phase 5: ledger verification --------------------------- #
        survivors = [n for n in nodes.values() if n is not victim]
        deadline = time.monotonic() + 60.0
        lost = keys
        while time.monotonic() < deadline:
            with ledger._lock:
                lost = [
                    k
                    for k in keys
                    if ledger.hist.get(k, -1) < ledger.acked.get(k, 0)
                ]
            if not lost:
                break
            for k in lost:
                a.cluster.entity_ref("chat", k).tell(("probe", ledger_cell))
            time.sleep(0.3)
        result["ledger"] = {
            "acked_commands": ledger.ack_total(),
            "sessions_verified": n_sessions - len(lost),
            "lost_acked": len(lost),
        }
        require(
            not lost,
            "bench.ledger",
            "acked state lost across the rolling restart",
            lost=lost[:5],
            n=len(lost),
        )
        result["journal"] = {
            node.name: node.cluster.journal.stats() for node in survivors
        }
    finally:
        stop.set()
        events.recorder.remove_listener(listener)
        events.recorder.disable()
        for node in nodes.values():
            try:
                node.system.terminate(timeout_s=5.0)
            except Exception:
                pass
        shutil.rmtree(journal_dir, ignore_errors=True)
        result.pop("journal_dir", None)
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--sessions", type=int, default=300)
    parser.add_argument(
        "--seconds", type=float, default=4.0, help="steady-phase duration"
    )
    parser.add_argument(
        "--partition",
        action="store_true",
        help="add the split-brain phase: partition one node mid-run "
        "(>= 10 heartbeat windows), verify quarantine + single-side "
        "serving, heal, and gate the ledger across it",
    )
    parser.add_argument(
        "--smoke", action="store_true", help="quick gate (60 sessions, 1s)"
    )
    args = parser.parse_args()
    if args.smoke:
        args.sessions, args.seconds = 60, 1.0
    result = run(args.sessions, args.seconds, partition=args.partition)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
