#!/usr/bin/env python
"""device-report: wake-budget attribution from the device observatory.

Renders the ``uigc.telemetry.device`` observatory document (the
``/device`` HTTP route) as the device-plane regression explainer:
per-wake device time decomposed sweep-by-sweep, the HBM/array memory
ledger with peak watermarks, compile-cache hit/miss streams (the
recompile-storm detector), host-transfer accounting per readback site
and wake phase, and the donation audit — then compares the measured
``device_per_wake_ms`` against the committed BENCH trajectory
(``BENCH_WAKE_r*.json`` / ``BENCH_TPU_SESSION_r*.json``) and prints the
top regressing plane (kernel tag or array family) first.

Sources:

- ``--url http://127.0.0.1:PORT``  a live node's metrics HTTP server
  (``uigc.telemetry.device`` + ``uigc.telemetry.http-port``);
- ``--from FILE``  a dumped observatory document (``--json`` output of
  a previous run, or a saved ``/device`` body);
- ``--demo``  a small in-process churn workload on the decremental
  device backend — the zero-to-report smoke;
- ``--selfcheck``  the verify-skill gate: drives the demo on the CPU
  backend and exits nonzero unless all three planes (ledger / compile /
  sweep attribution) produced nonzero, schema-valid output AND the
  per-sweep attribution totals reconcile with the wake profiler's
  device phase time within 10%.

The renderers are shared with ``tools/telemetry_dump.py --device`` and
the ``tools/uigc_top.py`` device panel.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
import urllib.request
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

# One dotted-path rule and one round regex for the whole BENCH
# trajectory — the gate (bench_check) and this report must resolve the
# committed figures identically, so the report imports the gate's.
from bench_check import _ROUND_RE, _resolve  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    v = float(n)
    for bound, suffix in ((1 << 30, "GiB"), (1 << 20, "MiB"), (1 << 10, "KiB")):
        if v >= bound:
            return f"{v / bound:.1f}{suffix}"
    return f"{int(v)}B"


# ------------------------------------------------------------------- #
# Committed trajectory (the comparison baseline)
# ------------------------------------------------------------------- #


def committed_device_figures(repo: str = REPO) -> Optional[Dict[str, Any]]:
    """The newest committed device-plane figures: scans the
    ``BENCH_WAKE_r*.json`` (wake_chain_bench dumps) and
    ``BENCH_TPU_SESSION_r*.json`` trajectories for ``device_per_wake_ms``
    / ``sweeps_mean`` / ``device_per_sweep_ms``.  Returns None when no
    committed round carries them (the honest no-TPU-rounds answer)."""
    # Families number their rounds independently, so never compare
    # round numbers ACROSS them: the WAKE family (wake_chain_bench's
    # own dumps) is the canonical device_per_wake_ms artifact and wins
    # outright; TPU sessions are the fallback for rounds where only the
    # session document was committed.
    for pattern in ("BENCH_WAKE_r*.json", "BENCH_TPU_SESSION_r*.json"):
        candidates: List[Tuple[int, str]] = []
        for path in glob.glob(os.path.join(repo, pattern)):
            match = _ROUND_RE.search(path)
            if match:
                candidates.append((int(match.group(1)), path))
        best: Optional[Dict[str, Any]] = None
        for _round, path in sorted(candidates):
            try:
                with open(path) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError):
                continue
            per_wake = _resolve(doc, "device_per_wake_ms")
            if per_wake is None:
                continue
            best = {
                "source": os.path.basename(path),
                "device_per_wake_ms": per_wake,
                "sweeps_mean": _resolve(doc, "sweeps_mean"),
                "device_per_sweep_ms": _resolve(doc, "device_per_sweep_ms"),
            }
        if best is not None:
            return best
    return None


# ------------------------------------------------------------------- #
# Analysis: the regression explainer
# ------------------------------------------------------------------- #


def measured_wake_figures(doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Aggregate device figures over the doc's device-active wakes."""
    wakes = [r for r in doc.get("recent_wakes", []) if r.get("device_s")]
    if not wakes:
        return None
    device_ms = sorted(r["device_s"] * 1000.0 for r in wakes)
    sweeps = [int(r["n_sweeps"]) for r in wakes if r.get("n_sweeps")]
    attributed = [
        (i, ms)
        for r in wakes
        for i, ms in enumerate(r.get("sweep_device_ms") or [])
    ]
    top_sweep = max(attributed, key=lambda t: t[1]) if attributed else None
    return {
        "wakes": len(wakes),
        "device_per_wake_ms": sum(device_ms) / len(device_ms),
        "device_per_wake_ms_p50": device_ms[len(device_ms) // 2],
        "sweeps_mean": (sum(sweeps) / len(sweeps)) if sweeps else None,
        "top_sweep": top_sweep,  # (sweep index, attributed ms)
    }


def findings(
    doc: Dict[str, Any], committed: Optional[Dict[str, Any]] = None
) -> List[Dict[str, str]]:
    """The explainer: ordered (severity, plane, label, detail) findings,
    worst first.  Deterministic rules, no magic — each names the plane
    and the kernel tag / array family / readback site to look at."""
    out: List[Dict[str, str]] = []

    # Compile plane: a tag missing repeatedly is a recompile storm —
    # one miss per geometry is the healthy shape.  Aggregated per TAG,
    # not per (tag, geom): the classic shape-key bug compiles a FRESH
    # geometry every wake, so each entry shows one innocent miss and
    # only the tag-level stream reveals the storm.
    per_tag: Dict[str, List[int]] = {}
    for entry in doc.get("compile", {}).get("entries", []):
        tag = str(entry.get("tag"))
        slot = per_tag.setdefault(tag, [0, 0, 0])
        slot[0] += int(entry.get("misses", 0))
        slot[1] += int(entry.get("hits", 0))
        slot[2] += 1
    for tag, (misses, hits, geoms) in sorted(per_tag.items()):
        if misses >= 3 and misses > hits:
            out.append({
                "severity": "critical",
                "plane": "compile",
                "label": tag,
                "detail": (
                    f"{misses} rebuilds vs {hits} hits across {geoms} "
                    "geometrie(s) — per-wake recompile (shape-key "
                    "churn); every wake pays a fresh compile"
                ),
            })

    # Donation audit: any copy is a real finding — the donating site is
    # paying double HBM traffic per wake.
    for site, count in sorted(
        (doc.get("donation", {}).get("sites") or {}).items()
    ):
        out.append({
            "severity": "warning",
            "plane": "donation",
            "label": site,
            "detail": (
                f"{count} donated buffer(s) survived their donating call "
                "(XLA copied instead of aliasing)"
            ),
        })

    # Transfer plane: readbacks landing OUTSIDE the trace bracket are
    # stray — ingest/fold/broadcast should never touch the device.
    for rec in doc.get("transfers", {}).get("sites", []):
        phase = rec.get("phase", "")
        if phase and phase not in ("trace", "sweep"):
            out.append({
                "severity": "warning",
                "plane": "transfer",
                "label": f"{rec.get('site')}@{phase}",
                "detail": (
                    f"{rec.get('count')} host transfer(s), "
                    f"{fmt_bytes(rec.get('bytes'))} inside the "
                    f"{phase!r} phase — a hot-path readback"
                ),
            })

    # Trajectory: measured per-wake device time vs the committed figure.
    measured = measured_wake_figures(doc)
    if measured and committed:
        prior = committed["device_per_wake_ms"]
        now = measured["device_per_wake_ms"]
        if prior > 0 and now > prior * 1.4:
            top = measured.get("top_sweep")
            sweep_note = (
                f"; heaviest sweep #{top[0]} at {top[1]:.2f}ms attributed"
                if top
                else ""
            )
            out.append({
                "severity": "critical",
                "plane": "wake_budget",
                "label": "device_per_wake_ms",
                "detail": (
                    f"{now:.2f}ms vs committed {prior:.2f}ms "
                    f"({committed['source']}){sweep_note}"
                ),
            })

    # Ledger: the family at its peak holding the most bytes (context
    # line, not an alarm: the ~700MB device-resident layout question).
    families = doc.get("ledger", {}).get("families", {})
    peaks = doc.get("ledger", {}).get("peaks", {})
    if families:
        fam, tally = max(
            families.items(), key=lambda kv: kv[1]["host"] + kv[1]["device"]
        )
        total = tally["host"] + tally["device"]
        out.append({
            "severity": "info",
            "plane": "ledger",
            "label": fam,
            "detail": (
                f"largest family: {fmt_bytes(total)} live "
                f"({fmt_bytes(tally['device'])} device-resident, "
                f"peak {fmt_bytes(peaks.get(fam, total))})"
            ),
        })
    severity_rank = {"critical": 0, "warning": 1, "info": 2}
    out.sort(key=lambda f: severity_rank.get(f["severity"], 3))
    return out


# ------------------------------------------------------------------- #
# Rendering (shared with telemetry_dump --device / uigc_top)
# ------------------------------------------------------------------- #


def render_device_doc(
    doc: Dict[str, Any], committed: Optional[Dict[str, Any]] = None
) -> str:
    lines: List[str] = []
    ledger = doc.get("ledger", {})
    stamp = time.strftime(
        "%H:%M:%S", time.localtime(doc.get("t", time.time()))
    )
    lines.append(
        f"device-report · {doc.get('node', '?')} · {stamp} · "
        f"{doc.get('wakes', 0)} wakes sampled"
    )
    lines.append("")

    flist = findings(doc, committed)
    alarms = [f for f in flist if f["severity"] != "info"]
    lines.append(
        f"findings ({len(alarms)} actionable):" if flist else "findings: none"
    )
    for f in flist:
        lines.append(
            f"  [{f['severity']:>8}] {f['plane']}/{f['label']}: {f['detail']}"
        )
    lines.append("")

    measured = measured_wake_figures(doc)
    lines.append("wake budget (device plane):")
    if measured:
        lines.append(
            f"  device_per_wake_ms  mean {measured['device_per_wake_ms']:.3f}"
            f"  p50 {measured['device_per_wake_ms_p50']:.3f}"
            f"  over {measured['wakes']} device-active wake(s)"
        )
        if measured["sweeps_mean"] is not None:
            lines.append(f"  sweeps_mean         {measured['sweeps_mean']:.2f}")
    else:
        lines.append("  (no device-active wakes recorded)")
    if committed:
        lines.append(
            f"  committed           {committed['device_per_wake_ms']:.3f}ms"
            f"/wake ({committed['source']})"
            + (
                f", sweeps_mean {committed['sweeps_mean']:.2f}"
                if committed.get("sweeps_mean") is not None
                else ""
            )
        )
    else:
        lines.append(
            "  committed           (no TPU round carries device_per_wake_ms"
            " — nothing to compare)"
        )
    # Sweep-by-sweep decomposition of the newest stats-bearing wake.
    stats_wakes = [
        r for r in doc.get("recent_wakes", []) if r.get("sweep_device_ms")
    ]
    if stats_wakes:
        r = stats_wakes[-1]
        lines.append(
            f"  newest decomposed wake: {int(r.get('n_sweeps', 0))} sweep(s),"
            f" device {r.get('device_s', 0.0) * 1000:.3f}ms"
        )
        dirty = r.get("sweep_dirty_chunks") or []
        for i, ms in enumerate(r["sweep_device_ms"]):
            extra = f"  dirty_chunks {dirty[i]}" if i < len(dirty) else ""
            best = r.get("sweep_bytes_est") or []
            est = f"  ~{fmt_bytes(best[i])}" if i < len(best) else ""
            lines.append(f"    sweep {i}: {ms:9.3f}ms{est}{extra}")
    lines.append("")

    lines.append("memory ledger:")
    families = ledger.get("families", {})
    peaks = ledger.get("peaks", {})
    if families:
        width = max(len(f) for f in families) + 2
        lines.append(
            f"  {'family'.ljust(width)}{'live':>10}{'device':>10}{'peak':>10}"
        )
        for fam in sorted(
            families, key=lambda f: -(families[f]["host"] + families[f]["device"])
        ):
            tally = families[fam]
            total = tally["host"] + tally["device"]
            lines.append(
                f"  {fam.ljust(width)}{fmt_bytes(total):>10}"
                f"{fmt_bytes(tally['device']):>10}"
                f"{fmt_bytes(peaks.get(fam, total)):>10}"
            )
        lines.append(
            f"  total {fmt_bytes(ledger.get('total_bytes'))} live, "
            f"{fmt_bytes(ledger.get('device_bytes'))} device-resident"
        )
    else:
        lines.append("  (no ledger samples yet)")
    lines.append("")

    lines.append("compile cache:")
    entries = doc.get("compile", {}).get("entries", [])
    if entries:
        for entry in entries:
            compile_s = entry.get("compile_s") or 0.0
            lines.append(
                f"  {entry.get('tag', '?'):<24} geom {entry.get('geom', '?'):<10}"
                f" hits {int(entry.get('hits', 0)):>6}"
                f" misses {int(entry.get('misses', 0)):>4}"
                + (f"  build {compile_s:.2f}s" if compile_s else "")
            )
        jx = doc.get("compile", {}).get("jax_backend", {})
        if jx.get("n"):
            lines.append(
                f"  xla backend_compile: {jx['n']} compile(s), "
                f"{jx['total_s']:.2f}s total, {jx['max_s']:.2f}s max"
            )
    else:
        lines.append("  (no compile-cache traffic observed)")
    lines.append("")

    lines.append("host transfers:")
    sites = doc.get("transfers", {}).get("sites", [])
    if sites:
        for rec in sites:
            phase = rec.get("phase") or "(no wake)"
            lines.append(
                f"  {rec.get('site', '?'):<24} {phase:<12}"
                f" n {int(rec.get('count', 0)):>6}"
                f"  {fmt_bytes(rec.get('bytes')):>10}"
            )
    else:
        lines.append("  none observed (transfer-free on the sampled window)")
    donation = doc.get("donation", {})
    if donation.get("copies_total"):
        lines.append("")
        lines.append(
            f"donation audit: {donation['copies_total']} silent cop(ies): "
            + ", ".join(
                f"{site}×{count}"
                for site, count in sorted(donation.get("sites", {}).items())
            )
        )
    return "\n".join(lines)


# ------------------------------------------------------------------- #
# Sources
# ------------------------------------------------------------------- #


def fetch_doc(base: str) -> Dict[str, Any]:
    with urllib.request.urlopen(base.rstrip("/") + "/device", timeout=10) as rsp:
        return json.loads(rsp.read())


class DemoSystem:
    """Decremental device backend under spawn/release churn with the
    observatory attached — enough cycles that the repair fixpoint runs
    real sweeps (the sweep-attribution plane needs n_sweeps >= 1)."""

    def __init__(self, extra_config: Optional[dict] = None):
        from uigc_tpu import (
            AbstractBehavior,
            ActorTestKit,
            Behaviors,
            NoRefs,
        )

        class Spawn(NoRefs):
            pass

        class Drop(NoRefs):
            pass

        class Worker(AbstractBehavior):
            def on_message(self, msg):
                return self

        outer = self

        class Root(AbstractBehavior):
            def __init__(self, context):
                super().__init__(context)
                self.held = []

            def on_message(self, msg):
                ctx = self.context
                if isinstance(msg, Spawn):
                    base = outer.spawned
                    outer.spawned += len_chain
                    self.held.extend(
                        ctx.spawn(Behaviors.setup(Worker), f"w{base + i}")
                        for i in range(len_chain)
                    )
                elif isinstance(msg, Drop) and self.held:
                    ctx.release(*self.held)
                    self.held = []
                return self

        len_chain = 24
        self.spawned = 0
        config = {
            "uigc.crgc.wakeup-interval": 10,
            "uigc.crgc.shadow-graph": "decremental",
            "uigc.telemetry.device": True,
            "uigc.telemetry.timeseries": True,
            "uigc.telemetry.ts-sample-interval": 100,
        }
        config.update(extra_config or {})
        self.kit = ActorTestKit(config=config, name="device-report-demo")
        self.root = self.kit.spawn(Behaviors.setup_root(Root), "root")
        self._spawn_msg, self._drop_msg = Spawn, Drop

    def churn(self, cycles: int = 5, settle_s: float = 0.2) -> None:
        for _ in range(cycles):
            self.root.tell(self._spawn_msg())
            time.sleep(settle_s)
            self.root.tell(self._drop_msg())
            time.sleep(settle_s)

    @property
    def telemetry(self):
        return self.kit.system.telemetry

    def shutdown(self) -> None:
        self.kit.shutdown()


def run_selfcheck() -> int:
    """The verify gate (CPU-backend smoke): all three planes nonzero,
    schema valid, attribution reconciles with the profiler's device
    phase within 10%."""
    from uigc_tpu.telemetry.device import validate_device_doc

    failures: List[str] = []
    demo = DemoSystem()
    try:
        # First collect pays jax init + the wake-fn build; churn after.
        time.sleep(2.0)
        demo.churn(cycles=6)
        deadline = time.time() + 30.0
        doc = demo.telemetry.observatory.to_doc()
        while time.time() < deadline:
            doc = demo.telemetry.observatory.to_doc()
            if any(r.get("n_sweeps") for r in doc["recent_wakes"]):
                break
            demo.churn(cycles=2)
        problems = validate_device_doc(doc)
        if problems:
            failures.append(f"schema: {problems}")
        if doc["wakes"] <= 0:
            failures.append("ledger plane: no wake samples")
        families = doc["ledger"]["families"]
        if not any(t["host"] + t["device"] for t in families.values()):
            failures.append("ledger plane: all families zero")
        if doc["compile"]["misses_total"] + doc["compile"]["hits_total"] <= 0:
            failures.append("compile plane: no cache traffic")
        stats_wakes = [r for r in doc["recent_wakes"] if r.get("n_sweeps")]
        if not stats_wakes:
            failures.append("sweep plane: no wake carried n_sweeps >= 1")
        for rec in stats_wakes:
            ms = rec.get("sweep_device_ms") or []
            device_ms = rec.get("device_s", 0.0) * 1000.0
            if ms and device_ms > 0:
                drift = abs(sum(ms) - device_ms) / device_ms
                if drift > 0.10:
                    failures.append(
                        f"attribution drift {drift:.1%} vs the profiler's "
                        f"device time on wake at t={rec.get('t')}"
                    )
        # The profiler's own view must agree in aggregate too.
        profiler = demo.telemetry.profiler
        prof_device_s = profiler.to_json()["phases"]["trace"]["device_total_s"]
        doc_device_s = sum(
            r.get("device_s", 0.0) for r in profiler.wakes_since(0.0)
        )
        if prof_device_s > 0:
            drift = abs(doc_device_s - prof_device_s) / prof_device_s
            # wakes_since is ring-bounded; only flag when it holds MORE
            # time than the running total (impossible) or the ring
            # covers everything yet disagrees.
            if doc_device_s > prof_device_s * 1.10:
                failures.append(
                    f"per-wake records exceed the profiler total by {drift:.1%}"
                )
        print(render_device_doc(doc, committed_device_figures()))
    finally:
        demo.shutdown()
    if failures:
        print("\ndevice-report selfcheck FAILED:", file=sys.stderr)
        for failure in failures:
            print(f"  - {failure}", file=sys.stderr)
        return 1
    print("\ndevice-report selfcheck OK", file=sys.stderr)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="device-report", description=__doc__.splitlines()[0]
    )
    source = parser.add_mutually_exclusive_group(required=True)
    source.add_argument(
        "--url", metavar="URL", help="live node base URL (http://host:port)"
    )
    source.add_argument(
        "--from", dest="from_file", metavar="FILE",
        help="a saved observatory document (/device body or --json output)",
    )
    source.add_argument(
        "--demo", action="store_true",
        help="drive a small churn workload and report on it",
    )
    source.add_argument(
        "--selfcheck", action="store_true",
        help="verify gate: demo + assert every plane produced "
        "schema-valid nonzero output (exit 1 otherwise)",
    )
    parser.add_argument(
        "--repo", default=REPO,
        help="repo root holding the committed BENCH trajectory",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the raw observatory document instead of the report",
    )
    args = parser.parse_args(argv)

    if args.selfcheck:
        return run_selfcheck()
    if args.demo:
        demo = DemoSystem()
        try:
            time.sleep(2.0)
            demo.churn(cycles=6)
            doc = demo.telemetry.observatory.to_doc()
        finally:
            demo.shutdown()
    elif args.from_file:
        with open(args.from_file) as fh:
            doc = json.load(fh)
    else:
        try:
            doc = fetch_doc(args.url)
        except Exception as exc:
            print(
                f"device-report: no /device at {args.url} "
                f"(uigc.telemetry.device off, or a node that predates the "
                f"observatory): {exc}",
                file=sys.stderr,
            )
            return 1
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True, default=repr))
        return 0
    print(render_device_doc(doc, committed_device_figures(args.repo)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
