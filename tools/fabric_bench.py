"""Fabric bench: two-node link throughput and bulk-teardown timing.

Measures the remote-messaging fast path (runtime/node.py writer
coalescing + the ``"fb"`` multi-frame wire units + the schema-native
codec + the co-located shm ring transport) against baselines on ONE
localhost node pair:

1. **shm**       — the full co-located fast path: schema-native codec
                   (runtime/schema.py run blocks) over the
                   shared-memory SPSC rings (runtime/shm_ring.py); no
                   socket syscalls, no pickle on the hot path.  This is
                   the mode the 250k+ frames/s acceptance bar — and the
                   500k ROADMAP target — is tracked on.
2. **batch**     — frame batching + schema codec over the socket (the
                   default for non-co-located peers).
3. **pickle**    — ``uigc.node.schema-codec: False``: the PR 5 wire
                   format exactly (fb batches of per-frame pickle
                   blocks) — isolates the codec's share of the win.
4. **singleton** — ``uigc.node.frame-batching: False`` on both nodes:
                   classic one-unit-per-frame wire format, one flush
                   per frame (what a batching node sends to a peer that
                   never advertised ``"fb"``).
5. **inline**    — the reconstructed PRE-WRITER transport: a faithful
                   copy of the old ``_send_frame`` that pickles the full
                   frame tuple and runs ``sendall`` while holding the
                   per-peer sequence lock, monkeypatched over the
                   NodeFabric of the sending node.

Plus a ``--payload-sizes`` sweep (shm mode, bytes payload appended to
each frame) and a **teardown** phase on a single node: K garbage actors
released at once, timed from release to full collection.

The link phases run with the CPython cyclic GC paused: the flood holds
~10^5 in-flight tuples, and gen-2 scans over that transient heap
dominate the measurement otherwise (observed: 100ms+ stalls, 3× noise).
Refcounting still reclaims every message; gc is re-enabled and
collected between phases.  PROFILING.md "Reading the codec mix" shows
how to see this effect live.

Prints one JSON object; commit as ``BENCH_FABRIC_r{N}.json``.

Usage: python tools/fabric_bench.py [--frames 20000] [--senders 4]
                                    [--actors 2000] [--transport both]
                                    [--payload-sizes 0,128,1024]
                                    [--smoke]
"""

from __future__ import annotations

import argparse
import gc
import io
import json
import pickle
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from uigc_tpu import AbstractBehavior, ActorSystem, Behaviors  # noqa: E402
from uigc_tpu.runtime.behaviors import RawBehavior  # noqa: E402
from uigc_tpu.runtime.node import NodeFabric, _frame_bytes  # noqa: E402
from uigc_tpu.utils import events  # noqa: E402
from uigc_tpu.utils.validation import require  # noqa: E402

#: The co-located serving profile: deeper writer queue + bigger drains
#: keep the senders out of the condition-variable backpressure path on
#: a flood, and a 256-message dispatcher slot amortizes scheduling.
#: All plain config keys — an operator gets the same profile by
#: setting them.
BASE = {
    "uigc.crgc.wakeup-interval": 25,
    "uigc.crgc.egress-finalize-interval": 10,
    "uigc.crgc.shadow-graph": "array",
    "uigc.crgc.num-nodes": 2,
    "uigc.runtime.throughput": 256,
    "uigc.node.max-batch-frames": 1024,
    "uigc.node.writer-queue-limit": 32768,
}

#: ROADMAP item 3's bar for this bench, recorded in the artifact so
#: bench_check trajectories carry the target alongside the measurement.
TARGET_FRAMES_PER_SEC = 500_000


class Sink(RawBehavior):
    """Counts bench frames; order violations would mean the seq layer
    let a reordered frame through (it must not)."""

    def __init__(self):
        self.n = 0
        self.order_violations = 0
        self._last = {}

    def on_message(self, msg):
        if isinstance(msg, tuple) and msg and msg[0] == "n":
            lane, i = msg[1], msg[2]
            if i <= self._last.get(lane, -1):
                self.order_violations += 1
            self._last[lane] = i
            self.n += 1
        return None


def _inline_enqueue_job(self, address, st, job):
    """The pre-writer transport, reconstructed at the job funnel: EVERY
    frame (app, marker, gossip, heartbeat) runs its egress stamp,
    sequence claim, fresh-pickler payload encode, full-frame pickle and
    ``sendall`` synchronously on the calling thread WHILE HOLDING the
    per-peer lock — so no writer thread ever starts and there is a
    single seq mutator, exactly the old shape.  Kept only as the
    measured baseline; the runtime itself no longer contains this
    pattern (tools/uigc_lint.py UL007 guards against it)."""
    from uigc_tpu.runtime import wire

    broken = False
    with st.lock:
        inner = self._job_inner(job)
        if inner is None:
            return
        if inner[0] == "app" and not isinstance(inner[2], bytes):
            # Fresh pickler per message, like the pre-pool wire codec.
            buf = io.BytesIO()
            wire._Pickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(inner[2])
            inner = (inner[0], inner[1], buf.getvalue()) + tuple(inner[3:])
        transmit = []
        self._apply_verdict(st, address, inner, inner[0], self.fault_plan, transmit)
        conn = self._conn_for(address)
        if conn is None:
            return
        for seq, frame, trunc in transmit:
            try:
                conn.send_bytes(_frame_bytes(("f", seq, frame), trunc))  # uigc-lint: disable=UL007
            except OSError:
                broken = True
                break
    if broken:
        self._on_conn_broken(address, conn)


#: mode -> config overrides; "inline" additionally monkeypatches the
#: sending fabric's job funnel (see _inline_enqueue_job).
MODES = {
    "inline": {"uigc.node.frame-batching": False, "uigc.node.schema-codec": False},
    "singleton": {"uigc.node.frame-batching": False, "uigc.node.schema-codec": False},
    "pickle": {"uigc.node.schema-codec": False},
    "batch": {},
    "shm": {"uigc.node.shm-transport": True},
}


def _inline_deliver(self, src, target, msg):
    """The pre-writer deliver: every app send goes through the job
    funnel (deliver() has since inlined the enqueue for speed, so the
    inline baseline must restore the funnel hop to stay faithful)."""
    from uigc_tpu.runtime import wire as wire_mod

    dst_address = target.system.address
    if self._conn_for(dst_address) is None:
        return
    header = wire_mod.encode_trace_header(msg)
    link = self._out_link(dst_address)
    st = self._peer_state(dst_address)
    self._enqueue_job(dst_address, st, ("a", link, target, msg, header))


class Pair:
    def __init__(self, name, mode):
        cfg = dict(BASE)
        cfg.update(MODES[mode])
        self.fa = NodeFabric()
        self.fb = NodeFabric()
        self.a = ActorSystem(None, name=f"{name}-a", config=cfg, fabric=self.fa)
        self.b = ActorSystem(None, name=f"{name}-b", config=cfg, fabric=self.fb)
        self.sink = Sink()
        sink_cell = self.b.spawn_system_raw(self.sink, "sink")
        self.fb.register_name("sink", sink_cell)
        port = self.fb.listen()
        if mode == "inline":
            # Patch ONLY the sending fabric's job funnel: the receive
            # side is the same singleton path either way.
            self.fa._enqueue_job = _inline_enqueue_job.__get__(self.fa)
            self.fa.deliver = _inline_deliver.__get__(self.fa)
        addr_b = self.fa.connect("127.0.0.1", port)
        self.proxy = self.fa.lookup(addr_b, "sink")
        if mode == "shm":
            deadline = time.monotonic() + 5
            while not self.fa.shm_active(addr_b) and time.monotonic() < deadline:
                time.sleep(0.005)
            require(
                self.fa.shm_active(addr_b),
                "fabric_bench.shm",
                "shm ring negotiation did not complete",
            )

    def close(self):
        for system in (self.a, self.b):
            try:
                system.terminate(timeout_s=5.0)
            except Exception:
                pass


def run_link_mode(mode: str, n_frames: int, n_senders: int, payload: int = 0) -> dict:
    pair = Pair(f"fbb-{mode}{payload and f'-p{payload}' or ''}", mode)
    batch_sizes = []
    codec = {"schema": 0, "pickle": 0}

    def listener(name, fields):
        if name == events.FRAME_BATCH:
            batch_sizes.append(fields.get("size", 0))
        elif name == events.CODEC_FRAMES:
            codec["schema"] += fields.get("schema", 0)
            codec["pickle"] += fields.get("pickle", 0)

    events.recorder.enable()
    events.recorder.add_listener(listener)
    try:
        per_sender = n_frames // n_senders
        total = per_sender * n_senders
        proxy = pair.proxy
        blob = b"x" * payload if payload else None

        def sender(lane):
            if blob is None:
                for i in range(per_sender):
                    proxy.tell(("n", lane, i))
            else:
                for i in range(per_sender):
                    proxy.tell(("n", lane, i, blob))

        threads = [
            threading.Thread(target=sender, args=(lane,)) for lane in range(n_senders)
        ]
        # Pause the cyclic GC for the timed flood (see module
        # docstring); refcounting still frees every message.
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Generous drain window: the inline baseline convoys down to a
        # few hundred frames/s on a bad run — that slowness is the
        # measurement, not a failure.
        deadline = time.monotonic() + 300
        while pair.sink.n < total and time.monotonic() < deadline:
            time.sleep(0.005)
        dt = time.perf_counter() - t0
        gc.enable()
        require(
            pair.sink.n == total,
            "fabric_bench.delivery",
            "not every bench frame was delivered",
            mode=mode,
            received=pair.sink.n,
            expected=total,
        )
        require(
            pair.sink.order_violations == 0,
            "fabric_bench.order",
            "the seq layer let a reordered frame through",
            mode=mode,
        )
        out = {
            "frames": total,
            "senders": n_senders,
            "seconds": dt,
            "frames_per_sec": total / dt,
        }
        if payload:
            out["payload_bytes"] = payload
        if mode in ("batch", "shm", "pickle"):
            out["writer_flushes"] = len(batch_sizes)
            out["mean_batch_size"] = (
                sum(batch_sizes) / len(batch_sizes) if batch_sizes else 0.0
            )
            out["max_batch_size"] = max(batch_sizes) if batch_sizes else 0
            out["codec_frames"] = dict(codec)
        return out
    finally:
        gc.enable()
        gc.collect()
        events.recorder.remove_listener(listener)
        events.recorder.disable()
        events.recorder.reset()
        pair.close()


class _Child(AbstractBehavior):
    def on_message(self, msg):
        return self

    def on_signal(self, signal):
        return None


class _Spawner(AbstractBehavior):
    """Root that spawns K children and releases them all on ("drop",)."""

    def __init__(self, context, k):
        super().__init__(context)
        self.children = [
            context.spawn(Behaviors.setup(lambda ctx: _Child(ctx)), f"c{i}")
            for i in range(k)
        ]

    def on_message(self, msg):
        if msg == ("drop",):
            self.context.release(*self.children)
            self.children = []
        return self

    def on_signal(self, signal):
        return None


def run_teardown(n_actors: int) -> dict:
    cfg = {
        "uigc.crgc.wakeup-interval": 10,
        "uigc.crgc.shadow-graph": "array",
    }
    system = ActorSystem(None, name="fbb-teardown", config=cfg)
    try:
        root = system.spawn_root(
            Behaviors.setup_root(lambda ctx: _Spawner(ctx, n_actors)), "spawner"
        )
        deadline = time.monotonic() + 60
        while (
            system.live_actor_count < n_actors + 4
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        base = system.live_actor_count - n_actors
        t0 = time.perf_counter()
        root.tell(("drop",))
        while system.live_actor_count > base and time.monotonic() < deadline:
            time.sleep(0.002)
        dt = time.perf_counter() - t0
        collected = n_actors - max(0, system.live_actor_count - base)
        require(
            collected == n_actors,
            "fabric_bench.teardown",
            "released actors were not all collected",
            collected=collected,
            expected=n_actors,
        )
        return {
            "actors": n_actors,
            "seconds": dt,
            "actors_per_sec": n_actors / dt,
        }
    finally:
        try:
            system.terminate(timeout_s=5.0)
        except Exception:
            pass


def run(
    n_frames: int,
    n_senders: int,
    n_actors: int,
    transport: str = "both",
    payload_sizes=(),
    smoke: bool = False,
    reps: int = 1,
) -> dict:
    result = {
        "frames": n_frames,
        "senders": n_senders,
        "target_frames_per_sec": TARGET_FRAMES_PER_SEC,
        "config": dict(BASE),
    }
    if smoke:
        modes = ["batch", "shm"]
    elif transport == "socket":
        modes = ["inline", "singleton", "pickle", "batch"]
    elif transport == "shm":
        modes = ["shm"]
    else:
        modes = ["inline", "singleton", "pickle", "batch", "shm"]

    def best_of(mode: str, payload: int = 0) -> dict:
        """Best of ``reps`` runs (every rep recorded): a 2-core CI box
        schedules these 7-thread pipelines with large run-to-run
        variance, and the bench tracks the transport's capability, not
        the host's scheduling luck.  ``reps`` rides the artifact so a
        trajectory reader sees exactly what was run."""
        n = max(1, reps) if mode in ("pickle", "batch", "shm") else 1
        runs = [
            run_link_mode(mode, n_frames, n_senders, payload=payload)
            for _ in range(n)
        ]
        best = max(runs, key=lambda r: r["frames_per_sec"])
        if len(runs) > 1:
            best = dict(best)
            best["reps"] = len(runs)
            best["all_frames_per_sec"] = [r["frames_per_sec"] for r in runs]
        return best

    result["reps"] = max(1, reps)
    result["link"] = {mode: best_of(mode) for mode in modes}
    link = result["link"]
    if "batch" in link and "inline" in link:
        result["speedup_vs_inline"] = (
            link["batch"]["frames_per_sec"] / link["inline"]["frames_per_sec"]
        )
    if "batch" in link and "singleton" in link:
        result["speedup_vs_singleton"] = (
            link["batch"]["frames_per_sec"] / link["singleton"]["frames_per_sec"]
        )
    if "shm" in link and "pickle" in link:
        result["shm_speedup_vs_pickle"] = (
            link["shm"]["frames_per_sec"] / link["pickle"]["frames_per_sec"]
        )
    sweep_mode = "shm" if transport in ("both", "shm") else "batch"
    sweep = {}
    for size in payload_sizes:
        if size <= 0:
            continue
        sweep[str(size)] = best_of(sweep_mode, payload=size)
    if sweep:
        result["payload_sweep"] = {"mode": sweep_mode, "sizes": sweep}
    result["teardown"] = run_teardown(n_actors)
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--frames", type=int, default=20000)
    parser.add_argument("--senders", type=int, default=4)
    parser.add_argument("--actors", type=int, default=2000)
    parser.add_argument(
        "--transport",
        choices=("socket", "shm", "both"),
        default="both",
        help="which link transports to measure (shm = co-located rings "
        "+ schema codec; socket keeps the r01-comparable modes)",
    )
    parser.add_argument(
        "--payload-sizes",
        default="",
        help="comma-separated extra payload bytes per frame to sweep "
        "(e.g. 128,1024,8192); swept on the shm mode when enabled",
    )
    parser.add_argument(
        "--reps",
        type=int,
        default=1,
        help="repetitions per link mode; the best run is reported (and "
        "every rep's frames/s recorded) — noisy small hosts",
    )
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="quick correctness pass (2k frames, 200 actors, batch+shm "
        "modes only); asserts delivery, ordering, shm negotiation and "
        "full teardown, not the speedup floor",
    )
    args = parser.parse_args()
    if args.smoke:
        args.frames, args.actors = 2000, 200
    sizes = [int(s) for s in args.payload_sizes.split(",") if s.strip()]
    result = run(
        args.frames,
        args.senders,
        args.actors,
        transport=args.transport,
        payload_sizes=sizes,
        smoke=args.smoke,
        reps=args.reps,
    )
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
