"""Fabric bench: two-node link throughput and bulk-teardown timing.

Measures the remote-messaging fast path (runtime/node.py writer
coalescing + the ``"fb"`` multi-frame wire units) against two baselines
on ONE localhost TCP pair:

1. **batch**     — frame batching on (the default): per-peer writer
                   coalesces queued frames into one ``"fb"`` unit per
                   flush; the receiver runs seq accounting per batch and
                   delivers app messages in per-cell runs.
2. **singleton** — ``uigc.node.frame-batching: False`` on both nodes:
                   same writer thread, but classic one-unit-per-frame
                   wire format and one flush per frame (what a batching
                   node sends to a peer that never advertised ``"fb"``).
3. **inline**    — the reconstructed PRE-WRITER transport: a faithful
                   copy of the old ``_send_frame`` that pickles the full
                   frame tuple and runs ``sendall`` while holding the
                   per-peer sequence lock, monkeypatched over the
                   NodeFabric of the sending node.  This is the ≥10×
                   acceptance baseline — the path where dispatcher
                   workers serialized on ``st.lock`` for the duration of
                   socket I/O.

Plus a **teardown** phase on a single node: K garbage actors released at
once, timed from release to full collection (the bulk stop-signal
cascade: one dispatcher submission per dispatcher, not per actor).

Prints one JSON object; commit as ``BENCH_FABRIC_r{N}.json``.

Usage: python tools/fabric_bench.py [--frames 20000] [--senders 4]
                                    [--actors 2000] [--smoke]
"""

from __future__ import annotations

import argparse
import io
import json
import pickle
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from uigc_tpu import AbstractBehavior, ActorSystem, Behaviors  # noqa: E402
from uigc_tpu.runtime.behaviors import RawBehavior  # noqa: E402
from uigc_tpu.runtime.node import NodeFabric, _frame_bytes  # noqa: E402
from uigc_tpu.utils import events  # noqa: E402
from uigc_tpu.utils.validation import require  # noqa: E402

BASE = {
    "uigc.crgc.wakeup-interval": 25,
    "uigc.crgc.egress-finalize-interval": 10,
    "uigc.crgc.shadow-graph": "array",
    "uigc.crgc.num-nodes": 2,
}


class Sink(RawBehavior):
    """Counts bench frames; order violations would mean the seq layer
    let a reordered frame through (it must not)."""

    def __init__(self):
        self.n = 0
        self.order_violations = 0
        self._last = {}

    def on_message(self, msg):
        if isinstance(msg, tuple) and msg and msg[0] == "n":
            lane, i = msg[1], msg[2]
            if i <= self._last.get(lane, -1):
                self.order_violations += 1
            self._last[lane] = i
            self.n += 1
        return None


def _inline_enqueue_job(self, address, st, job):
    """The pre-writer transport, reconstructed at the job funnel: EVERY
    frame (app, marker, gossip, heartbeat) runs its egress stamp,
    sequence claim, fresh-pickler payload encode, full-frame pickle and
    ``sendall`` synchronously on the calling thread WHILE HOLDING the
    per-peer lock — so no writer thread ever starts and there is a
    single seq mutator, exactly the old shape.  Kept only as the
    measured baseline; the runtime itself no longer contains this
    pattern (tools/uigc_lint.py UL007 guards against it)."""
    from uigc_tpu.runtime import wire

    broken = False
    with st.lock:
        inner = self._job_inner(job)
        if inner is None:
            return
        if inner[0] == "app" and not isinstance(inner[2], bytes):
            # Fresh pickler per message, like the pre-pool wire codec.
            buf = io.BytesIO()
            wire._Pickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(inner[2])
            inner = (inner[0], inner[1], buf.getvalue()) + tuple(inner[3:])
        transmit = []
        self._apply_verdict(st, address, inner, inner[0], self.fault_plan, transmit)
        conn = self._conn_for(address)
        if conn is None:
            return
        for seq, frame, trunc in transmit:
            try:
                conn.send_bytes(_frame_bytes(("f", seq, frame), trunc))  # uigc-lint: disable=UL007
            except OSError:
                broken = True
                break
    if broken:
        self._on_conn_broken(address, conn)


class Pair:
    def __init__(self, name, batching, inline=False):
        cfg = dict(BASE)
        if not batching:
            cfg["uigc.node.frame-batching"] = False
        self.fa = NodeFabric()
        self.fb = NodeFabric()
        self.a = ActorSystem(None, name=f"{name}-a", config=cfg, fabric=self.fa)
        self.b = ActorSystem(None, name=f"{name}-b", config=cfg, fabric=self.fb)
        self.sink = Sink()
        sink_cell = self.b.spawn_system_raw(self.sink, "sink")
        self.fb.register_name("sink", sink_cell)
        port = self.fb.listen()
        if inline:
            # Patch ONLY the sending fabric's job funnel: the receive
            # side is the same singleton path either way.
            self.fa._enqueue_job = _inline_enqueue_job.__get__(self.fa)
        addr_b = self.fa.connect("127.0.0.1", port)
        self.proxy = self.fa.lookup(addr_b, "sink")

    def close(self):
        for system in (self.a, self.b):
            try:
                system.terminate(timeout_s=5.0)
            except Exception:
                pass


def run_link_mode(mode: str, n_frames: int, n_senders: int) -> dict:
    pair = Pair(
        f"fbb-{mode}",
        batching=(mode == "batch"),
        inline=(mode == "inline"),
    )
    batch_sizes = []

    def listener(name, fields):
        if name == events.FRAME_BATCH:
            batch_sizes.append(fields.get("size", 0))

    events.recorder.enable()
    events.recorder.add_listener(listener)
    try:
        per_sender = n_frames // n_senders
        total = per_sender * n_senders
        proxy = pair.proxy

        def sender(lane):
            for i in range(per_sender):
                proxy.tell(("n", lane, i))

        threads = [
            threading.Thread(target=sender, args=(lane,)) for lane in range(n_senders)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Generous drain window: the inline baseline convoys down to a
        # few hundred frames/s on a bad run — that slowness is the
        # measurement, not a failure.
        deadline = time.monotonic() + 300
        while pair.sink.n < total and time.monotonic() < deadline:
            time.sleep(0.005)
        dt = time.perf_counter() - t0
        require(
            pair.sink.n == total,
            "fabric_bench.delivery",
            "not every bench frame was delivered",
            mode=mode,
            received=pair.sink.n,
            expected=total,
        )
        require(
            pair.sink.order_violations == 0,
            "fabric_bench.order",
            "the seq layer let a reordered frame through",
            mode=mode,
        )
        out = {
            "frames": total,
            "senders": n_senders,
            "seconds": dt,
            "frames_per_sec": total / dt,
        }
        if mode == "batch":
            out["writer_flushes"] = len(batch_sizes)
            out["mean_batch_size"] = (
                sum(batch_sizes) / len(batch_sizes) if batch_sizes else 0.0
            )
            out["max_batch_size"] = max(batch_sizes) if batch_sizes else 0
        return out
    finally:
        events.recorder.remove_listener(listener)
        events.recorder.disable()
        events.recorder.reset()
        pair.close()


class _Child(AbstractBehavior):
    def on_message(self, msg):
        return self

    def on_signal(self, signal):
        return None


class _Spawner(AbstractBehavior):
    """Root that spawns K children and releases them all on ("drop",)."""

    def __init__(self, context, k):
        super().__init__(context)
        self.children = [
            context.spawn(Behaviors.setup(lambda ctx: _Child(ctx)), f"c{i}")
            for i in range(k)
        ]

    def on_message(self, msg):
        if msg == ("drop",):
            self.context.release(*self.children)
            self.children = []
        return self

    def on_signal(self, signal):
        return None


def run_teardown(n_actors: int) -> dict:
    cfg = {
        "uigc.crgc.wakeup-interval": 10,
        "uigc.crgc.shadow-graph": "array",
    }
    system = ActorSystem(None, name="fbb-teardown", config=cfg)
    try:
        root = system.spawn_root(
            Behaviors.setup_root(lambda ctx: _Spawner(ctx, n_actors)), "spawner"
        )
        deadline = time.monotonic() + 60
        while (
            system.live_actor_count < n_actors + 4
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        base = system.live_actor_count - n_actors
        t0 = time.perf_counter()
        root.tell(("drop",))
        while system.live_actor_count > base and time.monotonic() < deadline:
            time.sleep(0.002)
        dt = time.perf_counter() - t0
        collected = n_actors - max(0, system.live_actor_count - base)
        require(
            collected == n_actors,
            "fabric_bench.teardown",
            "released actors were not all collected",
            collected=collected,
            expected=n_actors,
        )
        return {
            "actors": n_actors,
            "seconds": dt,
            "actors_per_sec": n_actors / dt,
        }
    finally:
        try:
            system.terminate(timeout_s=5.0)
        except Exception:
            pass


def run(n_frames: int, n_senders: int, n_actors: int) -> dict:
    result = {"frames": n_frames, "senders": n_senders}
    result["link"] = {
        mode: run_link_mode(mode, n_frames, n_senders)
        for mode in ("inline", "singleton", "batch")
    }
    link = result["link"]
    result["speedup_vs_inline"] = (
        link["batch"]["frames_per_sec"] / link["inline"]["frames_per_sec"]
    )
    result["speedup_vs_singleton"] = (
        link["batch"]["frames_per_sec"] / link["singleton"]["frames_per_sec"]
    )
    result["teardown"] = run_teardown(n_actors)
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--frames", type=int, default=20000)
    parser.add_argument("--senders", type=int, default=4)
    parser.add_argument("--actors", type=int, default=2000)
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="quick correctness pass (2k frames, 200 actors); asserts "
        "delivery, ordering and full teardown, not the speedup floor",
    )
    args = parser.parse_args()
    if args.smoke:
        args.frames, args.actors = 2000, 200
    result = run(args.frames, args.senders, args.actors)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
