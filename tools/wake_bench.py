"""Per-wake detection latency at graph scale under churn.

Models the collector's steady state (reference: LocalGC.scala:144-186, a
50ms-cadence incremental collect): a long-lived 10M-actor graph, and per
wake a batch of pair transitions (ref releases + new refs) folded into the
incremental Pallas layout in O(churn), then a device trace to fixpoint and
a compacted on-device reduction of garbage ids.  The full O(E log E) pack
runs once at startup; wakes pay only layout maintenance + the trace — the
layout's operand arrays stay device-resident between wakes
(IncrementalPallasLayout.trace_device) and sync in O(churn).

The JSON output reports p50/p90 of the host-maintenance, device-trace and
end-to-end wake times against BASELINE.md's <=10ms target, with the
device verdicts cross-checked against the numpy oracle on the first and
last wake.

Usage: python tools/wake_bench.py [--actors N] [--wakes 20]
       [--churn 20000] [--small]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--actors", type=int, default=None)
    ap.add_argument("--wakes", type=int, default=20)
    ap.add_argument("--churn", type=int, default=20_000)
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--no-oracle", action="store_true")
    ap.add_argument(
        "--mode",
        choices=["full", "decremental"],
        default="full",
        help=(
            "full: re-trace to fixpoint from seeds every wake "
            "(IncrementalPallasLayout.trace_device); decremental: "
            "closure+repair from the previous fixpoint "
            "(pallas_decremental.DecrementalTracer) — per-wake cost "
            "proportional to the churn's affected region"
        ),
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from uigc_tpu.models import powerlaw_actor_graph
    from uigc_tpu.ops import pallas_incremental as pinc
    from uigc_tpu.ops import trace as trace_ops
    from uigc_tpu.ops.slotmap import pack_keys
    from uigc_tpu.utils.platform import apply_platform_override, is_tpu_platform

    apply_platform_override()
    platform = jax.devices()[0].platform
    on_tpu = is_tpu_platform(platform)
    n = args.actors or (10_000_000 if on_tpu and not args.small else 1 << 16)

    rng = np.random.default_rng(7)
    graph = powerlaw_actor_graph(n, seed=0, garbage_fraction=0.5)
    flags = graph["flags"]
    recv = graph["recv_count"]

    t0 = time.perf_counter()
    if args.mode == "decremental":
        from uigc_tpu.ops.pallas_decremental import DecrementalTracer

        tracer = DecrementalTracer(n)
        layout = tracer.layout
        tracer.rebuild(
            graph["edge_src"], graph["edge_dst"], graph["edge_weight"],
            graph["supervisor"],
        )
    else:
        tracer = None
        layout = pinc.IncrementalPallasLayout(n)
        layout.rebuild(
            graph["edge_src"], graph["edge_dst"], graph["edge_weight"],
            graph["supervisor"],
        )
    rebuild_s = time.perf_counter() - t0

    # Base pair arrays (the churn population) + an oracle weight mask.
    psrc, pdst, kinds = pinc.IncrementalPallasLayout.pairs_from_graph(
        graph["edge_src"], graph["edge_dst"], graph["edge_weight"],
        graph["supervisor"],
    )
    base_keys_sorted = np.sort(pack_keys(psrc, pdst, kinds))
    removable = np.nonzero(kinds == 0)[0]  # churn stays edge-kind only
    removed = np.zeros(psrc.size, dtype=bool)
    ins_src: list = []
    ins_dst: list = []
    ins_seen: dict = {}

    in_use = (flags & trace_ops.FLAG_IN_USE) != 0
    id_cap = 1 << 17  # compacted garbage-id readback capacity

    @jax.jit
    def finish(mark, flags_dev):
        in_use_d = (flags_dev & trace_ops.FLAG_IN_USE) != 0
        garbage = in_use_d & (~mark)
        ids = jnp.nonzero(garbage, size=id_cap, fill_value=n)[0]
        return jnp.count_nonzero(garbage), ids

    flags_dev = jax.device_put(flags)
    recv_dev = jax.device_put(recv)

    if tracer is not None:
        from uigc_tpu.ops import pallas_trace as pt

        @jax.jit
        def unpack_marks(words):
            return pt.unpack_table(words, n, jnp)

    def run_wake():
        if tracer is not None:
            mark = unpack_marks(tracer.wake_device(flags_dev, recv_dev))
        else:
            mark = layout.trace_device(flags_dev, recv_dev)
        count, ids = finish(mark, flags_dev)
        return int(count), np.asarray(ids)

    def oracle_garbage():
        src = np.concatenate([psrc, np.asarray(ins_src, np.int64)])
        dst = np.concatenate([pdst, np.asarray(ins_dst, np.int64)])
        w = np.concatenate(
            [
                np.where(removed, 0, 1).astype(np.int64),
                np.ones(len(ins_src), np.int64),
            ]
        )
        m = trace_ops.trace_marks_np(
            flags, recv, np.full(n, -1, np.int32), src, dst, w
        )
        return int((in_use & ~m).sum())

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    # Warmup (compiles trace + readback; includes the first wake's pack).
    log(f"rebuild done in {rebuild_s:.1f}s; warmup trace...")
    count0, _ = run_wake()
    log(f"warmup done, garbage={count0}")
    checks = []
    if not args.no_oracle:
        checks.append(
            {"wake": "initial", "device": count0, "oracle": oracle_garbage()}
        )

    host_ms, trace_ms, wake_ms = [], [], []
    count = count0
    k = args.churn
    for w in range(args.wakes):
        # -- churn: half removals of live base pairs, half fresh inserts --
        cand = rng.choice(removable, k // 2, replace=False)
        cand = cand[~removed[cand]]
        new_s = rng.integers(0, n, k // 2, dtype=np.int64)
        new_d = rng.integers(0, n, k // 2, dtype=np.int64)
        new_keys = pack_keys(new_s, new_d, np.zeros(k // 2, np.int64))
        # skip inserts colliding with base pairs or earlier inserts
        pos = np.searchsorted(base_keys_sorted, new_keys)
        pos = np.minimum(pos, base_keys_sorted.size - 1)
        fresh = base_keys_sorted[pos] != new_keys

        log_batch = [
            (False, int(s), int(d), 0)
            for s, d in zip(psrc[cand].tolist(), pdst[cand].tolist())
        ]
        for key, s, d, f in zip(
            new_keys.tolist(), new_s.tolist(), new_d.tolist(), fresh.tolist()
        ):
            if not f or key in ins_seen:
                continue
            ins_seen[key] = None
            log_batch.append((True, s, d, 0))

        t0 = time.perf_counter()
        (tracer or layout).apply_log(log_batch)
        t1 = time.perf_counter()
        count, ids = run_wake()
        t2 = time.perf_counter()
        host_ms.append((t1 - t0) * 1e3)
        trace_ms.append((t2 - t1) * 1e3)
        wake_ms.append((t2 - t0) * 1e3)

        # mirror into the oracle state
        removed[cand] = True
        for ins, s, d, kind in log_batch:
            if ins:
                ins_src.append(s)
                ins_dst.append(d)
        log(
            f"wake {w}: host {host_ms[-1]:.1f}ms trace {trace_ms[-1]:.1f}ms "
            f"garbage={count}"
        )

    if not args.no_oracle:
        checks.append(
            {"wake": "final", "device": count, "oracle": oracle_garbage()}
        )

    ok = all(c["device"] == c["oracle"] for c in checks)
    p50 = statistics.median(wake_ms)
    result = {
        "bench": "per_wake_detection",
        "mode": args.mode,
        "n_actors": n,
        "n_pairs": int(layout.base["n_pairs"]),
        "wakes": args.wakes,
        "churn_per_wake": k,
        "platform": platform,
        "rebuild_s": round(rebuild_s, 2),
        "p50_wake_ms": round(p50, 2),
        "p90_wake_ms": round(sorted(wake_ms)[int(0.9 * len(wake_ms))], 2),
        "p50_host_maintenance_ms": round(statistics.median(host_ms), 2),
        "p50_trace_ms": round(statistics.median(trace_ms), 2),
        "layout_stats": {
            kk: (round(v, 3) if isinstance(v, float) else v)
            for kk, v in layout.stats.items()
        },
        "oracle_checks": checks,
        "oracle_ok": ok,
        "target_p50_ms": 10.0,
        "vs_target": round(10.0 / p50, 4),
    }
    print(json.dumps(result))
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
