"""Distributed-collector trace benchmark (engines/crgc/distributed.py).

A 3-node cluster running the partitioned collector: a master on node 0
spawns rings of workers — one worker per node, each holding a ref to
the next node's worker, so every ring is a garbage cycle that SPANS ALL
THREE NODES and no node's owned slice can prove it dead alone — then
drops every ring at once and times the distributed wave protocol
collecting them (boundary dmark exchange + Safra termination rounds,
no full-graph replica anywhere).

Reported:

- ``trace.garbage_actors_per_sec`` — cross-node garbage collected per
  second, drop to last PostStop (the headline figure);
- ``trace.boundary_mark_bytes_per_wave`` / ``trace.rounds_per_wave`` —
  the protocol's per-wave wire surface and termination cost;
- ``locality.max_node_population_fraction`` — the largest share of the
  global shadow population any single node held (owned + mirrors):
  materially below 1.0 is the whole point of the subsystem;
- ``replicated.garbage_actors_per_sec`` — the same workload on the
  replicated (full-copy) collector, for an apples-to-apples floor.

Prints one JSON object; commit as ``BENCH_DIST_r{N}.json`` (the
bench_check DIST family bands ``trace.garbage_actors_per_sec`` and
hard-zeroes ``trace.leaked_actors``).

Usage: python tools/dist_bench.py [--rings 120] [--waves 1]
       [--payload 0] [--reps 1] [--smoke] [--json PATH]

``--waves`` repeats the spawn/settle/drop/collect cycle (the drop
phases aggregate into the headline rate, so the bench_check bands see
a stable geometry instead of one cycle's jitter); ``--payload`` adds an
inert bytes blob to every ring-closing Hold message (scales the wire
traffic without changing the graph shape); ``--reps`` runs each phase
N times and reports the best by garbage rate with every rep's rate
listed (leaks are max-of, never hidden) — the whole collection is tens
of milliseconds, so a single rep is at the mercy of host noise.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from uigc_tpu import (  # noqa: E402
    AbstractBehavior,
    Behaviors,
    Message,
    NoRefs,
    PostStop,
)

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 10,
    "uigc.crgc.num-nodes": 3,
}

NODES = 3


class Hold(Message):
    """Hand a worker the ref that closes its ring (wire-crossing)."""

    def __init__(self, ref, blob=b""):
        self.ref = ref
        self.blob = blob

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class Go(NoRefs):
    def __init__(self, rings: int, payload: int = 0):
        self.rings = rings
        self.payload = payload


class Drop(NoRefs):
    pass


class Spawned(NoRefs):
    pass


class Stopped(NoRefs):
    pass


class Worker(AbstractBehavior):
    def __init__(self, context, probe_ref):
        super().__init__(context)
        self.probe_ref = probe_ref
        self.held = []
        probe_ref.tell(Spawned())

    def on_message(self, msg):
        if isinstance(msg, Hold):
            self.held.append(msg.ref)
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe_ref.tell(Stopped())
        return None


class Master(AbstractBehavior):
    def __init__(self, context, spawners):
        super().__init__(context)
        self.spawners = spawners
        self.workers = []

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Go):
            blob = b"\x5a" * msg.payload
            for _ in range(msg.rings):
                ring = [ctx.spawn_remote("worker", sc) for sc in self.spawners]
                n = len(ring)
                for i, w in enumerate(ring):
                    nxt = ring[(i + 1) % n]
                    w.tell(Hold(ctx.create_ref(nxt, w), blob), ctx)
                self.workers.extend(ring)
        elif isinstance(msg, Drop):
            for w in self.workers:
                ctx.release(w)
            self.workers = []
        return self


def _build(distributed: bool, probe):
    from uigc_tpu.runtime.fabric import Fabric
    from uigc_tpu.runtime.remote import RemoteSpawner
    from uigc_tpu.runtime.system import ActorSystem

    config = dict(BASE)
    config["uigc.crgc.distributed"] = distributed
    fabric = Fabric()
    systems = [
        ActorSystem(None, name=f"dist{i}", config=config, fabric=fabric)
        for i in range(NODES)
    ]
    spawners = [
        RemoteSpawner.spawn_service(
            s, {"worker": Behaviors.setup(lambda ctx: Worker(ctx, probe.ref))}
        )
        for s in systems
    ]
    master = systems[0].spawn_root(
        Behaviors.setup_root(lambda ctx: Master(ctx, spawners)), "master"
    )
    return systems, master


def _run_phase(
    rings: int,
    distributed: bool,
    timeout_s: float,
    waves: int = 1,
    payload: int = 0,
) -> dict:
    from uigc_tpu.runtime.testkit import TestProbe

    probe = TestProbe(default_timeout_s=timeout_s)
    systems, master = _build(distributed, probe)
    total = rings * NODES
    peak_pop = [0] * NODES
    peak_owned = [0] * NODES
    frac = {"pop": 0.0, "owned": 0.0}

    def sample():
        pops, owned = [], []
        for s in systems:
            g = s.engine.bookkeeper.shadow_graph
            pops.append(len(g.from_set))
            owned.append(g.owned_population())
        for i in range(NODES):
            peak_pop[i] = max(peak_pop[i], pops[i])
            peak_owned[i] = max(peak_owned[i], owned[i])
        # Fractions are judged against the GLOBAL census at the same
        # instant (every actor is owned exactly once, so the owned sum
        # is the global authoritative population) — a static
        # single-cycle denominator would let --waves carry-over (not-
        # yet-swept shadows from the prior cycle) inflate a node past
        # 1.0 and spuriously trip the bench_check ceiling.
        total = max(sum(owned), 1)
        frac["pop"] = max(frac["pop"], max(pops) / total)
        frac["owned"] = max(frac["owned"], max(owned) / total)

    try:
        stopped = 0
        elapsed = 0.0
        for _cycle in range(max(1, waves)):
            master.tell(Go(rings, payload))
            for _ in range(total):
                probe.expect_message_type(Spawned)
            # Let the held refs' entries reach every owner (and the
            # mirror-decay clock run) before the drop.
            time.sleep(0.3)
            if distributed:
                # Steady-state sample BEFORE the drop: this is the
                # moment every ring is resident, so a full-replica
                # regression (population fraction ~1.0) cannot hide
                # behind post-sweep sampling.  Pre-PR-15 the master's
                # owner legitimately neared 1.0 here (a hub's owner
                # held a bare mirror for every worker the master
                # referenced); mirror decay now returns it to ~the
                # owned fraction, which is what the band gates.
                sample()
            # Timed window with the cyclic collector paused (the PR 9
            # finding: ~10^5 in-flight objects trigger gen-2 storms
            # with ~100ms stalls — bimodal noise that swamps a
            # tens-of-ms measurement; refcounting covers the window).
            import gc as _gc

            _gc_was_enabled = _gc.isenabled()
            _gc.disable()
            t0 = time.monotonic()
            master.tell(Drop())
            cycle_stopped = 0
            deadline = t0 + timeout_s
            try:
                while cycle_stopped < total and time.monotonic() < deadline:
                    try:
                        probe.expect_message_type(Stopped)
                        cycle_stopped += 1
                    except Exception:
                        break
                    if distributed and cycle_stopped % 50 == 0:
                        sample()
                elapsed += max(time.monotonic() - t0, 1e-9)
            finally:
                if _gc_was_enabled:
                    _gc.enable()
            stopped += cycle_stopped
            if distributed:
                sample()
        total = total * max(1, waves)
        out = {
            "rings": rings,
            "cycles": max(1, waves),
            "payload_bytes": payload,
            "garbage_actors": stopped,
            "leaked_actors": total - stopped,
            "seconds": round(elapsed, 4),
            "garbage_actors_per_sec": round(stopped / elapsed, 1),
        }
        if distributed:
            dumps = [
                s.engine.bookkeeper.diagnostic_dump().get("distributed", {})
                for s in systems
            ]
            waves = max(1, max(d.get("waves_completed", 0) for d in dumps))
            out["waves"] = waves
            out["marks_sent"] = sum(d.get("marks_sent", 0) for d in dumps)
            out["mark_bytes"] = sum(d.get("mark_bytes", 0) for d in dumps)
            out["boundary_mark_bytes_per_wave"] = round(
                out["mark_bytes"] / waves, 1
            )
            out["rounds_total"] = sum(d.get("rounds_total", 0) for d in dumps)
            out["rounds_per_wave"] = round(out["rounds_total"] / waves, 2)
            out["boundary_edges_peak"] = max(
                d.get("boundary_edges", 0) for d in dumps
            )
            out["mirrors_evicted_total"] = sum(
                d.get("mirrors_evicted_total", 0) for d in dumps
            )
            out["node_peak_populations"] = peak_pop
            out["node_peak_owned"] = peak_owned
            out["max_node_population_fraction"] = round(frac["pop"], 3)
            out["max_node_owned_fraction"] = round(frac["owned"], 3)
        return out
    finally:
        for s in systems:
            s.terminate(timeout_s=10)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--rings", type=int, default=120)
    parser.add_argument(
        "--waves",
        type=int,
        default=1,
        help="spawn/drop cycles per phase (aggregated into one rate; "
        "gives the bench_check bands a stable geometry)",
    )
    parser.add_argument(
        "--payload",
        type=int,
        default=0,
        help="inert bytes carried by every ring-closing Hold message",
    )
    parser.add_argument(
        "--reps",
        type=int,
        default=1,
        help="repetitions per phase, best-of by garbage rate (the "
        "fabric_bench precedent: the workload is tens of ms, so a "
        "single rep is at the mercy of host noise)",
    )
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="small verify-gate run: a few rings, asserts zero leaks",
    )
    parser.add_argument("--json", type=str, default=None)
    args = parser.parse_args()

    rings = 6 if args.smoke else args.rings
    waves = 1 if args.smoke else max(1, args.waves)
    reps = 1 if args.smoke else max(1, args.reps)
    timeout_s = 60.0 if args.smoke else 180.0

    def best_of(distributed: bool) -> dict:
        runs = [
            _run_phase(
                rings, distributed=distributed, timeout_s=timeout_s,
                waves=waves, payload=args.payload,
            )
            for _ in range(reps)
        ]
        best = max(runs, key=lambda r: r["garbage_actors_per_sec"])
        best["reps"] = reps
        best["rep_rates"] = [r["garbage_actors_per_sec"] for r in runs]
        # Correctness/structural tallies are WORST-of across reps:
        # best-of may pick the fastest rate, but it must never hide a
        # leak or a mirror-decay regression observed in another rep.
        best["leaked_actors"] = max(r["leaked_actors"] for r in runs)
        for key in (
            "max_node_population_fraction",
            "max_node_owned_fraction",
        ):
            vals = [r[key] for r in runs if r.get(key) is not None]
            if key in best and vals:
                best[key] = max(vals)
        return best

    dist = best_of(distributed=True)
    result = {
        "bench": "dist",
        "nodes": NODES,
        "smoke": bool(args.smoke),
        "trace": dist,
        "locality": {
            "max_node_owned_fraction": dist.pop(
                "max_node_owned_fraction", None
            ),
            "max_node_population_fraction": dist.pop(
                "max_node_population_fraction", None
            ),
            "node_peak_owned": dist.pop("node_peak_owned", None),
            "node_peak_populations": dist.pop("node_peak_populations", None),
        },
    }
    if not args.smoke:
        repl = best_of(distributed=False)
        result["replicated"] = repl
        if repl["garbage_actors_per_sec"]:
            # The headline acceptance ratio: >= 1.0 means the
            # partitioned trace beats the replicated fold on the SAME
            # run/host (bench_check DIST floors it at 1.0).
            dist["speedup_vs_replicated"] = round(
                dist["garbage_actors_per_sec"]
                / repl["garbage_actors_per_sec"],
                3,
            )
    text = json.dumps(result, indent=2, sort_keys=True)
    print(text)
    if args.json:
        Path(args.json).write_text(text + "\n")
    if dist["leaked_actors"]:
        print(
            f"FAIL: {dist['leaked_actors']} of {rings * NODES * waves} "
            "cross-node cycle actors never collected",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
