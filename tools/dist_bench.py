"""Distributed-collector trace benchmark (engines/crgc/distributed.py).

A 3-node cluster running the partitioned collector: a master on node 0
spawns rings of workers — one worker per node, each holding a ref to
the next node's worker, so every ring is a garbage cycle that SPANS ALL
THREE NODES and no node's owned slice can prove it dead alone — then
drops every ring at once and times the distributed wave protocol
collecting them (boundary dmark exchange + Safra termination rounds,
no full-graph replica anywhere).

Reported:

- ``trace.garbage_actors_per_sec`` — cross-node garbage collected per
  second, drop to last PostStop (the headline figure);
- ``trace.boundary_mark_bytes_per_wave`` / ``trace.rounds_per_wave`` —
  the protocol's per-wave wire surface and termination cost;
- ``locality.max_node_population_fraction`` — the largest share of the
  global shadow population any single node held (owned + mirrors):
  materially below 1.0 is the whole point of the subsystem;
- ``replicated.garbage_actors_per_sec`` — the same workload on the
  replicated (full-copy) collector, for an apples-to-apples floor.

Prints one JSON object; commit as ``BENCH_DIST_r{N}.json`` (the
bench_check DIST family bands ``trace.garbage_actors_per_sec`` and
hard-zeroes ``trace.leaked_actors``).

Usage: python tools/dist_bench.py [--rings 120] [--smoke] [--json PATH]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from uigc_tpu import (  # noqa: E402
    AbstractBehavior,
    Behaviors,
    Message,
    NoRefs,
    PostStop,
)

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 10,
    "uigc.crgc.num-nodes": 3,
}

NODES = 3


class Hold(Message):
    """Hand a worker the ref that closes its ring (wire-crossing)."""

    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class Go(NoRefs):
    def __init__(self, rings: int):
        self.rings = rings


class Drop(NoRefs):
    pass


class Spawned(NoRefs):
    pass


class Stopped(NoRefs):
    pass


class Worker(AbstractBehavior):
    def __init__(self, context, probe_ref):
        super().__init__(context)
        self.probe_ref = probe_ref
        self.held = []
        probe_ref.tell(Spawned())

    def on_message(self, msg):
        if isinstance(msg, Hold):
            self.held.append(msg.ref)
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe_ref.tell(Stopped())
        return None


class Master(AbstractBehavior):
    def __init__(self, context, spawners):
        super().__init__(context)
        self.spawners = spawners
        self.workers = []

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Go):
            for _ in range(msg.rings):
                ring = [ctx.spawn_remote("worker", sc) for sc in self.spawners]
                n = len(ring)
                for i, w in enumerate(ring):
                    nxt = ring[(i + 1) % n]
                    w.tell(Hold(ctx.create_ref(nxt, w)), ctx)
                self.workers.extend(ring)
        elif isinstance(msg, Drop):
            for w in self.workers:
                ctx.release(w)
            self.workers = []
        return self


def _build(distributed: bool, probe):
    from uigc_tpu.runtime.fabric import Fabric
    from uigc_tpu.runtime.remote import RemoteSpawner
    from uigc_tpu.runtime.system import ActorSystem

    config = dict(BASE)
    config["uigc.crgc.distributed"] = distributed
    fabric = Fabric()
    systems = [
        ActorSystem(None, name=f"dist{i}", config=config, fabric=fabric)
        for i in range(NODES)
    ]
    spawners = [
        RemoteSpawner.spawn_service(
            s, {"worker": Behaviors.setup(lambda ctx: Worker(ctx, probe.ref))}
        )
        for s in systems
    ]
    master = systems[0].spawn_root(
        Behaviors.setup_root(lambda ctx: Master(ctx, spawners)), "master"
    )
    return systems, master


def _run_phase(rings: int, distributed: bool, timeout_s: float) -> dict:
    from uigc_tpu.runtime.testkit import TestProbe

    probe = TestProbe(default_timeout_s=timeout_s)
    systems, master = _build(distributed, probe)
    total = rings * NODES
    try:
        master.tell(Go(rings))
        for _ in range(total):
            probe.expect_message_type(Spawned)
        # Let the held refs' entries reach every owner before the drop.
        time.sleep(0.3)
        peak_pop = [0] * NODES
        peak_owned = [0] * NODES
        if distributed:
            # Steady-state sample BEFORE the drop: this is the moment
            # every ring is resident, so a full-replica regression
            # (owned fraction ~1.0) cannot hide behind post-sweep
            # sampling.  Note the master is a hub: its owner also holds
            # a bare MIRROR for every worker it spawned (endpoints of
            # the master's own edge list), so resident population on
            # that one node approaches the global count by design —
            # the ownership claim is about authoritative slots, which
            # is what the owned fraction measures and the band gates.
            for i, s in enumerate(systems):
                g = s.engine.bookkeeper.shadow_graph
                peak_pop[i] = max(peak_pop[i], len(g.from_set))
                peak_owned[i] = max(peak_owned[i], g.owned_population())
        t0 = time.monotonic()
        master.tell(Drop())
        stopped = 0
        deadline = t0 + timeout_s
        while stopped < total and time.monotonic() < deadline:
            try:
                probe.expect_message_type(Stopped)
                stopped += 1
            except Exception:
                break
            if distributed and stopped % 50 == 0:
                for i, s in enumerate(systems):
                    g = s.engine.bookkeeper.shadow_graph
                    peak_pop[i] = max(peak_pop[i], len(g.from_set))
        elapsed = max(time.monotonic() - t0, 1e-9)
        if distributed:
            for i, s in enumerate(systems):
                g = s.engine.bookkeeper.shadow_graph
                peak_pop[i] = max(peak_pop[i], len(g.from_set))
        out = {
            "rings": rings,
            "garbage_actors": stopped,
            "leaked_actors": total - stopped,
            "seconds": round(elapsed, 4),
            "garbage_actors_per_sec": round(stopped / elapsed, 1),
        }
        if distributed:
            dumps = [
                s.engine.bookkeeper.diagnostic_dump().get("distributed", {})
                for s in systems
            ]
            waves = max(1, max(d.get("waves_completed", 0) for d in dumps))
            out["waves"] = waves
            out["marks_sent"] = sum(d.get("marks_sent", 0) for d in dumps)
            out["mark_bytes"] = sum(d.get("mark_bytes", 0) for d in dumps)
            out["boundary_mark_bytes_per_wave"] = round(
                out["mark_bytes"] / waves, 1
            )
            out["rounds_total"] = sum(d.get("rounds_total", 0) for d in dumps)
            out["rounds_per_wave"] = round(out["rounds_total"] / waves, 2)
            out["boundary_edges_peak"] = max(
                d.get("boundary_edges", 0) for d in dumps
            )
            # Workers + one spawner per node + the master; the probe
            # rides its own system outside the cluster.
            global_pop = rings * NODES + NODES + 1
            out["node_peak_populations"] = peak_pop
            out["node_peak_owned"] = peak_owned
            out["max_node_population_fraction"] = round(
                max(peak_pop) / max(global_pop, 1), 3
            )
            out["max_node_owned_fraction"] = round(
                max(peak_owned) / max(global_pop, 1), 3
            )
        return out
    finally:
        for s in systems:
            s.terminate(timeout_s=10)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--rings", type=int, default=120)
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="small verify-gate run: a few rings, asserts zero leaks",
    )
    parser.add_argument("--json", type=str, default=None)
    args = parser.parse_args()

    rings = 6 if args.smoke else args.rings
    timeout_s = 60.0 if args.smoke else 180.0
    dist = _run_phase(rings, distributed=True, timeout_s=timeout_s)
    result = {
        "bench": "dist",
        "nodes": NODES,
        "smoke": bool(args.smoke),
        "trace": dist,
        "locality": {
            "max_node_owned_fraction": dist.pop(
                "max_node_owned_fraction", None
            ),
            "max_node_population_fraction": dist.pop(
                "max_node_population_fraction", None
            ),
            "node_peak_owned": dist.pop("node_peak_owned", None),
            "node_peak_populations": dist.pop("node_peak_populations", None),
        },
    }
    if not args.smoke:
        repl = _run_phase(rings, distributed=False, timeout_s=timeout_s)
        result["replicated"] = repl
    text = json.dumps(result, indent=2, sort_keys=True)
    print(text)
    if args.json:
        Path(args.json).write_text(text + "\n")
    if dist["leaked_actors"]:
        print(
            f"FAIL: {dist['leaked_actors']} of {rings * NODES} "
            "cross-node cycle actors never collected",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
