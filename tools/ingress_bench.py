"""Ingress bench: the gateway front door under a 10x overload storm.

Two data nodes serve a sharded counter keyspace; one proxy-only
gateway node terminates real TCP client connections and routes SENDs
into the entity plane.  Two phases, printed as one JSON object:

1. **connections** — connection scale: open several hundred concurrent
   client connections (CONNECT -> AUTH_OK each) against one gateway,
   report the peak concurrently-terminated count and the handshake
   rate.  The selector-loop architecture is the thing under test: the
   gateway must hold the whole set on a fixed thread budget.
2. **overload** — the admission contract: with the per-tenant token
   bucket defining admitted capacity (``uigc.gateway.tenant-msgs-per-
   sec``), clients drive SEND traffic at ~10x that capacity.  The
   asymmetric promise under storm:

   - ADMITTED commands keep their latency: ack p50/p99 (ms);
   - SHED commands get a clean, seq-addressed, retryable ERROR frame
     (``clean_shed_fraction`` of all non-acked sends — no silent
     drops, no torn frames, no closed-without-answer);
   - ``acked_then_lost`` is a hard zero: after the storm every key is
     probed and its entity count must cover every ACK the clients
     recorded — an ACK for state the entity does not hold would be a
     durability lie.

Commit as ``BENCH_INGRESS_r{N}.json``; bench_check's INGRESS family
gates admitted_p99_ms (absolute ceiling), clean_shed_fraction (floor),
acked_then_lost (hard zero from the debut round), connections
per_gateway (floor) and the throughput figures by trajectory.

Usage: python tools/ingress_bench.py [--connections 600] [--seconds 4]
       [--capacity 300] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import socket
import struct
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from uigc_tpu import ActorSystem, ClusterSharding, Entity  # noqa: E402
from uigc_tpu.gateway import IngressGateway, protocol  # noqa: E402
from uigc_tpu.runtime.node import NodeFabric  # noqa: E402
from uigc_tpu.utils.validation import require  # noqa: E402

_LEN = struct.Struct(">I")


def base_config(capacity_msgs_per_sec: int) -> dict:
    return {
        "uigc.crgc.wakeup-interval": 50,
        "uigc.crgc.egress-finalize-interval": 10,
        "uigc.crgc.shadow-graph": "array",
        "uigc.crgc.num-nodes": 3,
        "uigc.cluster.tick-interval": 40,
        "uigc.cluster.handoff-retry": 150,
        "uigc.runtime.throughput": 256,
        "uigc.node.max-batch-frames": 1024,
        "uigc.node.writer-queue-limit": 32768,
        # The admission plane under test: the token bucket IS the
        # definition of admitted capacity the storm multiplies.
        "uigc.gateway.tenant-msgs-per-sec": capacity_msgs_per_sec,
        "uigc.gateway.tenant-max-connections": 4096,
        "uigc.gateway.egress-queue-limit": 1024,
        "uigc.gateway.reader-threads": 2,
    }


class CounterEntity(Entity):
    """Counts gateway commands; the ACK result is the count AFTER the
    apply, so a later probe can verify no acked increment vanished."""

    def __init__(self, ctx, key, state):
        super().__init__(ctx, key)
        self.count = (state or {}).get("count", 0)

    def receive(self, msg):
        if isinstance(msg, tuple) and msg and msg[0] == "gw-cmd":
            _kind, ref, seq, cmd = msg
            if not (isinstance(cmd, dict) and cmd.get("probe")):
                self.count += 1
            ref.tell(("ack", seq, self.count))
        return self

    def snapshot_state(self):
        return {"count": self.count}


def counter_factory(ctx, key, state):
    return CounterEntity(ctx, key, state)


def percentile(samples, p):
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(round(p / 100.0 * (len(ordered) - 1))))
    return ordered[idx]


def settle(predicate, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return predicate()


# ------------------------------------------------------------------- #
# Minimal raw-framing client
# ------------------------------------------------------------------- #


def _read_one_frame(sock: socket.socket, timeout_s: float = 10.0):
    """Blocking read of exactly one raw frame -> (op, value)."""
    sock.settimeout(timeout_s)
    buf = b""
    while len(buf) < 4:
        chunk = sock.recv(4 - len(buf))
        if not chunk:
            raise ConnectionError("gateway closed during handshake")
        buf += chunk
    (n,) = _LEN.unpack(buf)
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            raise ConnectionError("gateway closed mid-frame")
        body += chunk
    return protocol.decode_frame_body(body)


class BenchClient:
    """One raw-TCP client connection with a background reader tallying
    ACK latency and seq-addressed ERROR frames."""

    def __init__(self, host: str, port: int, tenant: str = "bench"):
        self.sock = socket.create_connection((host, port))
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.lock = threading.Lock()
        self.sent_at = {}
        self.acked = {}  # seq -> (result, latency_s)
        self.errors = {}  # seq -> error code
        self.anon_errors = []  # ERROR frames without a seq
        self.closed = False
        self.auth_ok = threading.Event()
        self.reader = threading.Thread(target=self._read_loop, daemon=True)
        self.reader.start()
        self.sock.sendall(
            protocol.encode_frame(
                protocol.OP_CONNECT, {"tenant": tenant, "proto": 1}
            )
        )
        require(
            self.auth_ok.wait(10.0),
            "bench.connect",
            "gateway never answered CONNECT with AUTH_OK",
        )

    def _read_loop(self):
        buf = bytearray()
        sock = self.sock
        while True:
            try:
                data = sock.recv(65536)
            except OSError:
                data = b""
            if not data:
                self.closed = True
                return
            buf += data
            while len(buf) >= 4:
                (n,) = _LEN.unpack_from(buf, 0)
                if len(buf) < 4 + n:
                    break
                body = bytes(buf[4 : 4 + n])
                del buf[: 4 + n]
                op, value = protocol.decode_frame_body(body)
                now = time.perf_counter()
                if op == protocol.OP_AUTH_OK:
                    self.auth_ok.set()
                elif op == protocol.OP_ACK and isinstance(value, dict):
                    seq = value.get("seq")
                    with self.lock:
                        t0 = self.sent_at.get(seq)
                        self.acked[seq] = (
                            value.get("result"),
                            (now - t0) if t0 is not None else 0.0,
                        )
                elif op == protocol.OP_ERROR and isinstance(value, dict):
                    with self.lock:
                        if "seq" in value:
                            self.errors[value["seq"]] = value.get("code")
                        else:
                            self.anon_errors.append(value.get("code"))

    def send_cmd(self, seq: int, key: str, cmd) -> None:
        frame = protocol.encode_frame(
            protocol.OP_SEND,
            {"seq": seq, "type": "counter", "key": key, "cmd": cmd},
        )
        with self.lock:
            self.sent_at[seq] = time.perf_counter()
        self.sock.sendall(frame)

    def outstanding(self) -> int:
        with self.lock:
            return len(self.sent_at) - len(self.acked) - len(self.errors)

    def close(self):
        # shutdown() before close(): the reader thread blocks in recv()
        # holding a reference to the fd, so a bare close() would defer
        # the FIN until that thread drains -- which it never does, since
        # it is waiting for the very FIN.  Shutdown sends it immediately.
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


# ------------------------------------------------------------------- #
# Phases
# ------------------------------------------------------------------- #


def connection_scale_phase(host: str, port: int, gateway, n_conns: int) -> dict:
    """Open ``n_conns`` concurrent connections (full CONNECT->AUTH_OK
    handshake each, no reader threads — the sockets just sit), then
    report the gateway's peak terminated count."""
    socks = []
    connect_frame = protocol.encode_frame(
        protocol.OP_CONNECT, {"tenant": "scale", "proto": 1}
    )
    t0 = time.perf_counter()
    try:
        for _ in range(n_conns):
            sock = socket.create_connection((host, port))
            sock.sendall(connect_frame)
            op, _value = _read_one_frame(sock)
            require(
                op == protocol.OP_AUTH_OK,
                "bench.scale",
                f"expected AUTH_OK, got op {op}",
            )
            socks.append(sock)
        elapsed = time.perf_counter() - t0
        peak = gateway.connection_count()
    finally:
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass
    settle(lambda: gateway.connection_count() == 0, 15.0)
    return {
        "opened": len(socks),
        "per_gateway": peak,
        "seconds": elapsed,
        "connect_per_sec": len(socks) / elapsed if elapsed > 0 else 0.0,
    }


def overload_phase(
    host: str,
    port: int,
    gateway,
    capacity: int,
    seconds: float,
    n_clients: int,
    n_keys: int,
) -> dict:
    clients = [BenchClient(host, port) for _ in range(n_clients)]
    keys = [f"k-{i}" for i in range(n_keys)]
    target_rate = capacity * 10  # the 10x storm, all clients combined
    per_client = max(1, target_rate // n_clients)
    stop = threading.Event()
    seq_base = 1_000_000

    def storm(ci: int, client: BenchClient):
        # Paced bursts: BURST sends, then sleep whatever keeps this
        # client at its share of the 10x rate.
        burst = 32
        interval = burst / per_client
        seq = seq_base * (ci + 1)
        i = 0
        while not stop.is_set():
            t_burst = time.perf_counter()
            try:
                for _ in range(burst):
                    client.send_cmd(seq, keys[(seq + ci) % n_keys], {"op": "inc"})
                    seq += 1
            except OSError:
                return
            i += 1
            sleep_for = interval - (time.perf_counter() - t_burst)
            if sleep_for > 0:
                time.sleep(sleep_for)

    threads = [
        threading.Thread(target=storm, args=(ci, c), daemon=True)
        for ci, c in enumerate(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    storm_s = time.perf_counter() - t0
    # Drain: every in-flight send resolves to an ACK or an ERROR.
    settle(lambda: all(c.outstanding() == 0 for c in clients), 15.0)

    sent = sum(len(c.sent_at) for c in clients)
    ack_entries = [
        (seq, result, lat)
        for c in clients
        for seq, (result, lat) in c.acked.items()
    ]
    acked = len(ack_entries)
    error_seqs = sum(len(c.errors) for c in clients)
    unresolved = sum(c.outstanding() for c in clients)
    shed = sent - acked
    latencies = [lat for _seq, _result, lat in ack_entries]

    # Max acked count per key: ACK results are the entity count after
    # each apply, so the final probe must read >= the max acked value.
    max_acked: dict = {}
    for ci, client in enumerate(clients):
        with client.lock:
            items = list(client.acked.items())
        for seq, (result, _lat) in items:
            key = keys[(seq + ci) % n_keys]
            if isinstance(result, int) and result > max_acked.get(key, 0):
                max_acked[key] = result

    # Probe every key through the same front door (quota refills at
    # capacity/s, so retry through any rate-shed).
    prober = clients[0]
    probe_seq = 1
    finals: dict = {}
    deadline = time.monotonic() + 30.0
    for key in keys:
        while time.monotonic() < deadline:
            seq = probe_seq
            probe_seq += 1
            prober.send_cmd(seq, key, {"probe": True})
            settle(
                lambda: seq in prober.acked or seq in prober.errors, 5.0
            )
            if seq in prober.acked:
                finals[key] = prober.acked[seq][0]
                break
            time.sleep(0.2)  # rate-shed: wait for bucket refill
    acked_then_lost = sum(
        1
        for key, high in max_acked.items()
        if not isinstance(finals.get(key), int) or finals[key] < high
    )

    result = {
        "capacity_msgs_per_sec": capacity,
        "target_multiple": 10,
        "clients": n_clients,
        "keys": n_keys,
        "seconds": storm_s,
        "sent": sent,
        "acked": acked,
        "admitted_per_sec": acked / storm_s if storm_s > 0 else 0.0,
        "admitted_p50_ms": percentile(latencies, 50) * 1e3,
        "admitted_p99_ms": percentile(latencies, 99) * 1e3,
        "shed": shed,
        "clean_shed_errors": error_seqs,
        "unresolved": unresolved,
        "clean_shed_fraction": (error_seqs / shed) if shed else 1.0,
        "keys_probed": len(finals),
        "acked_then_lost": acked_then_lost,
    }
    for client in clients:
        client.close()
    settle(lambda: gateway.connection_count() == 0, 15.0)
    return result


# ------------------------------------------------------------------- #
# Driver
# ------------------------------------------------------------------- #


class DataNode:
    __slots__ = ("name", "fabric", "system", "cluster", "region", "port")

    def __init__(self, name: str, config: dict):
        self.name = name
        self.fabric = NodeFabric()
        self.system = ActorSystem(
            None, name=name, config=config, fabric=self.fabric
        )
        self.port = self.fabric.listen()
        self.cluster = ClusterSharding.attach(self.system)
        self.region = self.cluster.start("counter", counter_factory)


def run(n_conns: int, seconds: float, capacity: int) -> dict:
    config = base_config(capacity)
    nodes = [DataNode(f"ingress-data-{i}", config) for i in range(2)]
    gw_fabric = NodeFabric()
    gw_system = ActorSystem(
        None, name="ingress-gw", config=config, fabric=gw_fabric
    )
    gw_fabric.listen()
    gw_cluster = ClusterSharding.attach(gw_system, proxy_only=True)
    gateway = IngressGateway(gw_system)
    result: dict = {}
    try:
        nodes[0].fabric.connect("127.0.0.1", nodes[1].port)
        gw_fabric.connect("127.0.0.1", nodes[0].port)
        gw_fabric.connect("127.0.0.1", nodes[1].port)
        require(
            settle(
                lambda: len(gw_cluster.members()) == 2
                and all(len(n.cluster.members()) == 2 for n in nodes)
            ),
            "bench.membership",
            "2 data nodes + proxy gateway never settled",
        )
        require(
            settle(lambda: gw_cluster.home_of("k-0") is not None),
            "bench.table",
            "gateway never adopted a shard table",
        )
        client_port = gateway.listen()
        result["connections"] = connection_scale_phase(
            "127.0.0.1", client_port, gateway, n_conns
        )
        result["overload"] = overload_phase(
            "127.0.0.1",
            client_port,
            gateway,
            capacity,
            seconds,
            n_clients=4,
            n_keys=32,
        )
        require(
            result["overload"]["acked_then_lost"] == 0,
            "bench.acked-lost",
            "an acked command's state vanished",
            overload=result["overload"],
        )
        result["gateway_stats"] = dict(gateway.stats)
    finally:
        gateway.close()
        for sysm in [gw_system] + [n.system for n in nodes]:
            try:
                sysm.terminate(timeout_s=5.0)
            except Exception:
                pass
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--connections", type=int, default=600)
    parser.add_argument(
        "--seconds", type=float, default=4.0, help="overload storm duration"
    )
    parser.add_argument(
        "--capacity",
        type=int,
        default=150,
        help="admitted tenant msgs/sec; the storm drives 10x this.  "
        "Keep it comfortably below the host's end-to-end entity "
        "throughput: the bench's p99 band asserts that ADMITTED "
        "traffic stays fast, which only holds when admission control "
        "(this quota) keeps the offered load inside capacity — a "
        "quota at or above capacity just moves the queue inside and "
        "the tail measures backlog, not the gateway",
    )
    parser.add_argument(
        "--smoke", action="store_true", help="quick gate (80 conns, 1s)"
    )
    args = parser.parse_args()
    if args.smoke:
        args.connections, args.seconds, args.capacity = 80, 1.0, 200
    result = run(args.connections, args.seconds, args.capacity)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
