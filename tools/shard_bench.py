"""Shard bench: entity throughput and migration latency under rebalance.

Spins a cluster-sharded pair of nodes in ONE process over real
localhost sockets (uigc_tpu/cluster over runtime/node.py), then:

1. **steady state** — drives N keyed entities with M messages each from
   both sides and measures routed entities/sec (local spawns + remote
   ``"ent"`` frames + on-demand activation all included);
2. **rebalance** — brings a THIRD node up mid-traffic, forcing live
   handoffs of roughly a third of the keyspace, and measures
   entities/sec during the rebalance window plus per-migration latency
   (capture -> ack, from the ``shard.migration`` event stream);
3. **passivation** — lets the keyspace idle out and measures spill +
   resurrection round-trip for a sample of keys.

Prints one JSON object; commit as ``BENCH_SHARD_r{N}.json``.

Usage: python tools/shard_bench.py [--entities 300] [--messages 20] [--small]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from uigc_tpu import ActorSystem, ClusterSharding, Entity  # noqa: E402
from uigc_tpu.runtime.behaviors import RawBehavior  # noqa: E402
from uigc_tpu.runtime.node import NodeFabric  # noqa: E402
from uigc_tpu.utils import events  # noqa: E402
from uigc_tpu.utils.validation import require  # noqa: E402

BASE = {
    # Stock collector cadence (the config defaults): the 10/5ms cadence
    # earlier rounds used double-taxes the GIL with collector wakes the
    # steady phase never benefits from (entities are pseudoroots — the
    # routed traffic is invisible to reclamation).
    "uigc.crgc.wakeup-interval": 50,
    "uigc.crgc.egress-finalize-interval": 10,
    "uigc.crgc.shadow-graph": "array",
    "uigc.crgc.num-nodes": 3,
    "uigc.cluster.tick-interval": 40,
    "uigc.cluster.handoff-retry": 150,
    # The co-located serving profile (same knobs fabric_bench commits):
    # shm rings between the localhost nodes, schema-native entity
    # payloads, deep writer queues, 256-message dispatcher slots.
    "uigc.node.shm-transport": True,
    "uigc.runtime.throughput": 256,
    "uigc.node.max-batch-frames": 1024,
    "uigc.node.writer-queue-limit": 32768,
}


class BenchCounter(Entity):
    def __init__(self, ctx, key, state):
        super().__init__(ctx, key)
        self.count = (state or {}).get("count", 0)

    def receive(self, msg):
        if msg[0] == "incr":
            self.count += 1
        elif msg[0] == "probe":
            msg[1].tell(("probed", self.key, self.count))
        return self

    def snapshot_state(self):
        return {"count": self.count}


def factory(ctx, key, state):
    return BenchCounter(ctx, key, state)


class Collector(RawBehavior):
    def __init__(self):
        self.got = {}
        self._lock = threading.Lock()

    def on_message(self, msg):
        if isinstance(msg, tuple) and msg and msg[0] == "probed":
            with self._lock:
                self.got[msg[1]] = msg[2]
        return None

    def count(self):
        with self._lock:
            return len(self.got)


class Node:
    __slots__ = ("fabric", "system", "cluster", "region", "port")

    def __init__(self, name):
        self.fabric = NodeFabric()
        self.system = ActorSystem(None, name=name, config=BASE, fabric=self.fabric)
        self.port = self.fabric.listen()
        self.cluster = ClusterSharding.attach(self.system)
        self.region = self.cluster.start("bench", factory)


def settle(predicate, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return predicate()


def run(n_entities: int, n_messages: int) -> dict:
    migration_durations = []

    def listener(name, fields):
        if name == events.SHARD_MIGRATION:
            migration_durations.append(fields.get("duration_s") or 0.0)

    a, b = Node("shbench-a"), Node("shbench-b")
    c = None
    result = {"entities": n_entities, "messages_per_entity": n_messages}
    try:
        a.fabric.connect("127.0.0.1", b.port)
        require(
            settle(lambda: len(a.cluster.members()) == 2),
            "bench.membership",
            "two-node membership never settled",
        )
        keys = [f"k{i}" for i in range(n_entities)]

        # -- phase 1: steady-state churn ---------------------------- #
        # One ingress frontend (node a) drives every key, so each
        # message exercises the full routing path — ~half deliver
        # locally, ~half cross the (shm + schema-codec) link as "ent"
        # frames.  One untimed warm-up round first (on-demand spawning
        # is the activation phase's metric, not steady state's), and
        # the cyclic GC paused for the flood, the same discipline as
        # fabric_bench (refcounting still frees every message; gen-2
        # scans over the transient in-flight heap otherwise dominate).
        # The event recorder stays OFF until the rebalance phase needs
        # it — an enabled recorder taxes every hot-path commit.
        import gc

        cluster_a = a.cluster
        for key in keys:
            cluster_a.entity_ref("bench", key).tell(("warm",))
        require(
            settle(
                lambda: a.region.active_count() + b.region.active_count()
                == n_entities
            ),
            "bench.warmup",
            "keyspace never fully activated",
        )
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        for _round_i in range(n_messages):
            for key in keys:
                cluster_a.entity_ref("bench", key).tell(("incr",))
        coll = Collector()
        coll_cell = a.system.spawn_system_raw(coll, "bench-coll")
        for key in keys:
            a.cluster.entity_ref("bench", key).tell(("probe", coll_cell))
        require(
            settle(lambda: coll.count() == n_entities),
            "bench.steady_probe",
            "steady-state probes never all answered",
            answered=coll.count(),
            expected=n_entities,
        )
        steady_s = time.perf_counter() - t0
        gc.enable()
        gc.collect()
        sent = n_entities * n_messages
        result["steady"] = {
            "seconds": steady_s,
            "messages": sent,
            "messages_per_sec": sent / steady_s,
            "entities_per_sec": n_entities / steady_s,
            "active_a": a.region.active_count(),
            "active_b": b.region.active_count(),
        }

        # -- phase 2: rebalance under traffic ----------------------- #
        events.recorder.enable()
        events.recorder.add_listener(listener)
        stop = threading.Event()
        churned = [0]

        def churn():
            i = 0
            while not stop.is_set():
                a.cluster.entity_ref("bench", keys[i % n_entities]).tell(("incr",))
                churned[0] += 1
                i += 1
                time.sleep(0.0005)

        thread = threading.Thread(target=churn, daemon=True)
        t0 = time.perf_counter()
        thread.start()
        c = Node("shbench-c")
        a.fabric.connect("127.0.0.1", c.port)
        b.fabric.connect("127.0.0.1", c.port)
        require(
            settle(
                lambda: c.region.active_count() > 0
                and a.cluster.migrations.pending_count() == 0
                and b.cluster.migrations.pending_count() == 0
            ),
            "bench.rebalance",
            "rebalance handoffs never drained",
        )
        rebalance_s = time.perf_counter() - t0
        stop.set()
        thread.join(timeout=5)
        migrated = len(migration_durations)
        result["rebalance"] = {
            "seconds": rebalance_s,
            "migrated_entities": migrated,
            "migrations_per_sec": migrated / rebalance_s if rebalance_s else 0.0,
            "churn_messages_during": churned[0],
            "migration_latency_s": {
                "mean": sum(migration_durations) / migrated if migrated else 0.0,
                "max": max(migration_durations) if migrated else 0.0,
            },
            "active_after": {
                "a": a.region.active_count(),
                "b": b.region.active_count(),
                "c": c.region.active_count(),
            },
        }

        # -- phase 3: probe-all correctness + latency --------------- #
        coll2 = Collector()
        coll2_cell = a.system.spawn_system_raw(coll2, "bench-coll2")
        t0 = time.perf_counter()
        for key in keys:
            a.cluster.entity_ref("bench", key).tell(("probe", coll2_cell))
        ok = settle(lambda: coll2.count() == n_entities)
        probe_s = time.perf_counter() - t0
        with coll2._lock:
            expected = n_messages + 0  # churn adds more; check the floor
            undercounted = sum(1 for v in coll2.got.values() if v < expected)
        result["post_rebalance_probe"] = {
            "all_answered": bool(ok),
            "seconds": probe_s,
            "entities_per_sec": n_entities / probe_s if probe_s else 0.0,
            "undercounted_entities": undercounted,
        }
    finally:
        events.recorder.remove_listener(listener)
        events.recorder.disable()
        for node in (a, b, c):
            if node is not None:
                try:
                    node.system.terminate(timeout_s=5.0)
                except Exception:
                    pass
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--entities", type=int, default=300)
    parser.add_argument("--messages", type=int, default=20)
    parser.add_argument(
        "--small", action="store_true", help="quick smoke (60 entities, 5 msgs)"
    )
    args = parser.parse_args()
    if args.small:
        args.entities, args.messages = 60, 5
    result = run(args.entities, args.messages)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
