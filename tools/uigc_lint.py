#!/usr/bin/env python
"""uigc-lint: AST-based static checks for actor code and the runtime.

Catches the protocol mistakes that produce silent GC unsoundness or
scheduling hangs — the static half of the correctness tooling whose
online half is ``uigc_tpu/analysis`` (uigcsan).  Runs on the repo
itself (``python tools/uigc_lint.py --strict uigc_tpu/``) and on user
actor code.

Rules
=====

UL001  ref-captured-in-closure
    A name that looks like an actor ref (``*ref*``) is captured by a
    closure passed to ``Behaviors.setup``/``spawn`` inside a behavior
    method, with no ``create_ref`` call in the enclosing function.
    Handing a refob to another actor without registering it with
    ``context.create_ref`` breaks CRGC's created/released pairing: the
    collector never learns the new owner and may collect a live actor.

UL002  message-refs-not-exported
    A class deriving ``Message`` stores constructor parameters that
    look like refs but its ``refs`` property returns a constant empty
    tuple (or the class derives ``NoRefs`` while storing refs).
    Refs that ride a message invisibly are not counted at the ingress
    and leak (or over-collect) across nodes.

UL003  blocking-call-in-behavior
    A blocking call (``time.sleep``, ``socket.recv``, ``.join()``,
    ``queue.get``, ``Event.wait``, ``input``) inside a behavior
    callback (``on_message``/``on_signal`` or a ``Behaviors.setup``
    closure).  Behavior callbacks run on the shared dispatcher pool; a
    blocked callback starves every other actor on that thread.

UL004  bare-assert-invariant
    A bare ``assert`` guarding a runtime invariant in library code.
    Asserts are stripped under ``python -O``; invariants must raise
    structured errors (``uigc_tpu/utils/validation.py``) that carry the
    mismatching entries.  (Asserts in ``tests/`` are fine and not
    linted.)

UL005  inconsistent-lock-order
    Two locks are acquired in opposite nesting orders somewhere across
    the analyzed files (``with a_lock: ... with b_lock:`` here,
    ``with b_lock: ... with a_lock:`` there) — the classic deadlock
    shape.  Lock identity is approximated by attribute name, so locks
    sharing a name across unrelated classes can alias; suppress a
    false pair with the comment syntax below.

UL006  direct-proxycell-construction
    ``ProxyCell(...)`` constructed outside ``runtime/``.  A ProxyCell
    is the transport's cached identity handle for a remote uid — the
    fabric's ``_proxy`` cache guarantees one instance per (address,
    uid), which the shadow graph relies on to fold one remote actor to
    one slot.  A hand-built ProxyCell bypasses the cache (two handles,
    two slots, wrong balances) and pins a raw uid that passivation or
    migration may retire at any time.  Entity-addressed code must go
    through ``EntityRef`` (uigc_tpu/cluster); transport-level code that
    really needs a proxy goes through ``fabric._proxy``.

UL007  socket-io-under-peer-lock
    A blocking socket call (``sendall``/``send_bytes``/``recv``/
    ``accept``/``connect``/``create_connection``) lexically inside a
    ``with`` block holding a ``_PeerState`` lock (``st.lock`` /
    ``st.rlock``, or any ``.lock``/``.rlock`` on a name bound from
    ``_peer_state(...)``).  This is the transport convoy the writer
    refactor removed: every dispatcher worker sending to that peer
    serializes on the lock for the DURATION of socket I/O, so one slow
    link stalls the whole mutator plane.  Sequence claims and fault
    verdicts belong under the lock; encoding and socket writes belong
    on the per-peer writer thread, off-lock.  Grandfathered nowhere —
    new occurrences always fail ``--strict``.

UL010  pickle-on-runtime-hot-path
    A direct ``pickle.dumps``/``loads``/``dump``/``load``/``Pickler``/
    ``Unpickler`` call in a ``runtime/`` module other than ``wire.py``
    (the sanctioned codec module, whose pickle use IS the negotiated
    fallback).  PR 9 made pickle the fallback, not the default: known
    message shapes cross links schema-native (runtime/schema.py), and
    a stray pickle call on a hot-path module silently reintroduces the
    per-message protocol dispatch the codec removed — or worse, emits
    bytes a peer's negotiated decoder will not recognize.  Encode
    through ``wire.encode_message_schema``/``wire.encode_message``;
    legacy transport framing sites are grandfathered in the allowlist.

UL009  metric-name-convention
    A metric registered at a ``registry.counter/gauge/histogram(...)``
    call site (any receiver, first argument a string literal) whose
    name does not carry the ``uigc_`` prefix, or — for counters and
    histograms — no unit suffix (``_seconds``/``_bytes``/``_total``/
    ``_ratio``).  Gauges are exempt from the unit suffix (a gauge's
    unit is its referent: actors, frames, phi), but not the prefix.
    Unprefixed names collide in shared Prometheus scrapes; unitless
    names make dashboards guess.  Registrations built from a non-literal
    first argument are not linted (nothing to check statically).

UL011  unguarded-host-transfer
    A device->host crossing idiom (``jax.device_get(...)``, a zero-arg
    ``.item()`` call, or ``np.asarray(x)`` WITHOUT a ``dtype=`` keyword
    — the dtype'd form is the host list-conversion idiom, the bare form
    is how device arrays get read back) in a module under ``engines/``,
    ``ops/`` or ``parallel/`` with no ``# readback:`` annotation on the
    line.  Stray transfers on collector hot paths serialize the device
    pipeline and dodge the observatory's transfer accounting
    (uigc_tpu/telemetry/device.py); deliberate crossings route through
    ``engines/crgc/arrays._readback`` (accounted) or carry a
    ``# readback: <why>`` annotation.  Legacy conversion sites in the
    ops layer are grandfathered in the allowlist.

UL008  inspector-mutates-engine-state
    Snapshot/inspect code (``uigc_tpu/telemetry/inspect.py``) broke its
    read-only contract.  The liveness inspector observes the collector's
    graph from foreign threads (HTTP handlers, link receive threads, the
    CLI); any mutation it performed would race the collector fold and
    corrupt liveness state — so the module must not (a) import
    ``uigc_tpu.engines``/``uigc_tpu.runtime`` at runtime (TYPE_CHECKING-
    gated imports are fine: duck-typed access only), (b) store through an
    attribute whose root object is not ``self`` (its own recorder/
    watchdog state is fair game, a graph/cell/system handle is not), or
    (c) call an engine mutator (``trace``/``merge_*``/``start_wave``/
    ``tell``/``stop``/``collect``/``send_frame``/...).  Capture
    *enablement* is engine state and lives with the engine
    (``Telemetry.attach``, ``engines/crgc/collector.py``).

UL016  pickle-in-gateway
    A direct ``pickle.*``/``marshal.*`` serializer call anywhere under
    ``uigc_tpu/gateway/``.  The ingress gateway sits on the untrusted
    side of the trust boundary: client bytes must only ever meet the
    closed client value codec (``schema.encode_client_value`` /
    ``decode_client_value`` — no code loading, bounded depth/size),
    and node-plane replies cross back through ``runtime/wire.py``
    helpers.  A code-loading deserializer in gateway code is one
    routing bug away from running on attacker-controlled bytes, so it
    is banned outright there (the static half of uigc-check's UC401
    reachability rule).

Suppression
===========

Append ``# uigc-lint: disable=UL001`` (comma-separate several codes,
or ``disable=all``) to the offending line.  Legacy violations are
grandfathered in an allowlist file (default: ``uigc_lint_allow.txt``
next to this script) of ``path:RULE:count`` budget lines — ``--strict``
fails only on violations beyond the budget, so new code stays clean
while old debt is burned down deliberately.

Exit status: 0 when clean, within budget, or running advisory (no
``--strict``); 1 on new violations under ``--strict``; 2 usage error.
"""

# ------------------------------------------------------------------- #
# This file is a thin wrapper.  The rule implementations moved to the
# shared single-parse framework in uigc_tpu/analysis/check/ (one
# ast.parse per file, shared with the surface/lock/purity passes of
# uigc-check); rule ids, messages, suppression syntax and allowlist
# semantics are bit-compatible with the standalone linter this file
# used to be.  `python tools/uigc_check.py --rules 'UL*' ...` runs the
# same pass with the same verdicts.
# ------------------------------------------------------------------- #

import os
import sys
from typing import Iterable, List

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from uigc_tpu.analysis.check import core as _core  # noqa: E402
from uigc_tpu.analysis.check import lint_rules as _lint_rules  # noqa: E402

#: structured finding; uigc-check calls the same type Diagnostic
Violation = _core.Diagnostic
RULES = _lint_rules.RULES

iter_py_files = _core.iter_py_files
_load_allowlist = _core.load_allowlist
apply_allowlist = _core.apply_allowlist


def lint_paths(
    paths: Iterable[str],
    lint_asserts: bool = True,
) -> List[Violation]:
    files, errors = _core.parse_paths(paths)
    return list(errors) + _lint_rules.run_lint(files, lint_asserts=lint_asserts)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="uigc-lint", description=__doc__.splitlines()[0]
    )
    parser.add_argument("paths", nargs="+", help="files or directories to lint")
    parser.add_argument(
        "--strict",
        action="store_true",
        help="exit nonzero on violations beyond the allowlist budget "
        "(the default run is advisory: report, exit 0)",
    )
    parser.add_argument(
        "--allowlist",
        default=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "uigc_lint_allow.txt"
        ),
        help="path:RULE:count budget file (default: uigc_lint_allow.txt "
        "next to this script)",
    )
    parser.add_argument(
        "--no-allowlist", action="store_true", help="ignore the allowlist"
    )
    parser.add_argument(
        "--select",
        default="",
        help="comma-separated rule codes to report (default: all)",
    )
    args = parser.parse_args(argv)

    violations = lint_paths(args.paths)
    if args.select:
        wanted = {c.strip().upper() for c in args.select.split(",")}
        violations = [v for v in violations if v.rule in wanted]
    budget = {} if args.no_allowlist else _load_allowlist(args.allowlist)
    grandfathered, fresh = apply_allowlist(violations, budget)

    for v in fresh:
        print(v.render())
    if grandfathered:
        print(
            f"uigc-lint: {len(grandfathered)} grandfathered violation(s) "
            f"suppressed by allowlist",
            file=sys.stderr,
        )
    if fresh:
        print(f"uigc-lint: {len(fresh)} new violation(s)", file=sys.stderr)
        return 1 if args.strict else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
